#!/usr/bin/env bash
# The one-command pre-merge gate: static analysis, tier-1 tests, and
# the native sanitizer build. Each stage that cannot run in the current
# environment skips LOUDLY instead of failing silently.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tpukube-lint (static analysis: lock discipline/order, shared"
echo "   state, name consistency + registry reverse audit, exception"
echo "   hygiene, CFG dataflow: epoch discipline + reservation leaks +"
echo "   seam triples, flag discipline, stale waivers) =="
python -m tpukube.analysis tpukube
# the grown pass families must stay REGISTERED: a rule dropping out of
# the runner (a lost ALL_RULES entry, a broken import) would make the
# clean exit above trivially meaningless for that family
rule_listing="$(python -m tpukube.analysis --list-rules)"
for rule in seam-triple flag-discipline name-consistency epoch-discipline; do
  grep -q "^${rule} " <<<"${rule_listing}" || {
    echo "tpukube-lint: rule ${rule} missing from --list-rules" >&2
    exit 1
  }
done

echo
echo "== tier-1 tests =="
# The one deselected test is known-environment-sensitive (hbmguard
# quota accounting under the CI allocator) — see ROADMAP.md's tier-1
# note. The former jax-CPU-training deselect is gone: train_step no
# longer donates buffers on the CPU backend (XLA CPU mis-aliases
# donated sharded buffers), so the loss-decreases assertion runs at
# full strength everywhere. Everything else must pass.
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  -p no:cacheprovider \
  --deselect tests/test_config3.py::test_config3_quota_accumulates_not_just_single_alloc

echo
echo "== chaos smoke (scenarios 8-9: seeded apiserver chaos + crash"
echo "   recovery; zero leaked reservations / zero ledger divergence) =="
# fixed seed so the fault sequence — and therefore the pass — is
# reproducible; the scenarios raise (non-zero exit) on any invariant
# violation
JAX_PLATFORMS=cpu TPUKUBE_CHAOS_SEED=1337 \
  python -m tpukube.cli sim 8 > /dev/null
JAX_PLATFORMS=cpu python -m tpukube.cli sim 9 > /dev/null

echo
echo "== maintenance-storm smoke (scenario 15: seeded maintenance +"
echo "   spot-churn storm over the drain choreography, the autoscaler"
echo "   loop, and a sharded rebalance-away, at snapshot_audit_rate=1.0;"
echo "   zero leaks / zero ledger divergence / all-or-nothing gang"
echo "   survival / disruption within budget enforced by the scenario —"
echo "   elasticity floors from tools/perf_floor.json) =="
JAX_PLATFORMS=cpu TPUKUBE_CHAOS_SEED=1337 TPUKUBE_SNAPSHOT_AUDIT_RATE=1.0 \
  python - <<'PY'
import json
import sys
import time

floor = json.load(open("tools/perf_floor.json"))["elasticity"]

from tpukube.sim import scenarios

# the scenario itself raises on invariant violations (eviction over the
# per-tick budget, a gang left partially alive, leaked reservations,
# ledger or audit divergence, autoscaler mis-decisions); the floors
# below catch drain-cost rot
t0 = time.perf_counter()
r = scenarios.run(15)
wall = round(time.perf_counter() - t0, 2)
print(json.dumps({
    "drains_survived": r["value"],
    "peak_tick_moves": r["peak_tick_moves"],
    "budget_moves": r["budget_moves"],
    "audit": r["snapshot_audit"], "wall_s": wall,
}))
bad = []
if r["value"] < floor["drains_survived_min"]:
    bad.append(f"drains_survived={r['value']} below the "
               f"{floor['drains_survived_min']} floor")
if wall > floor["wall_s_max"]:
    bad.append(f"wall_s={wall} exceeds the {floor['wall_s_max']}s "
               f"ceiling")
if r["snapshot_audit"]["checks"] < 1:
    bad.append("the audit sentinel never checked a storm snapshot")
if bad:
    sys.exit("maintenance-storm smoke FAILED: " + "; ".join(bad))
print("maintenance-storm smoke OK")
PY

JAX_PLATFORMS=cpu python - <<'PY'
import json
import sys

floor = json.load(open("tools/perf_floor.json"))["elasticity"]

import bench

# the direct elasticity points: one graceful drain of a resident-loaded
# slice (drained-chips/s) and the 10,240-node bulk scale-up until the
# new capacity is visible to the placement sweeps
r = bench.elasticity()
print(json.dumps({k: r[k] for k in (
    "drain_wall_s", "drain_evictions", "drained_chips_per_s",
    "scale_up_10k_to_capacity_s")}))
bad = []
if r["drained_chips_per_s"] < floor["drained_chips_per_s_min"]:
    bad.append(f"drained_chips_per_s={r['drained_chips_per_s']} below "
               f"the {floor['drained_chips_per_s_min']}/s floor")
if r["scale_up_10k_to_capacity_s"] > floor["scale_up_to_capacity_s_max"]:
    bad.append(f"scale_up_10k_to_capacity_s="
               f"{r['scale_up_10k_to_capacity_s']} exceeds the "
               f"{floor['scale_up_to_capacity_s_max']}s ceiling")
if bad:
    sys.exit("elasticity smoke FAILED: " + "; ".join(bad))
print("elasticity smoke OK")
PY

echo
echo "== perf smoke (sched_micro filter/prioritize/plan p50 vs the"
echo "   committed tools/perf_floor.json floor; >1.5x regression fails) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import sys

import bench

floor = json.load(open("tools/perf_floor.json"))
m = bench.sched_micro()
print(json.dumps(
    {k: v for k, v in sorted(m.items()) if k != "mesh"}, indent=None))
bad = []
for key, base in floor["p50_ms_floor"].items():
    if m[key] > base * floor["allowed_regression"]:
        bad.append(f"{key}={m[key]:.3f}ms exceeds floor {base}ms "
                   f"x {floor['allowed_regression']}")
for key, need in floor.get("min_speedup", {}).items():
    if m[key] < need:
        bad.append(f"{key}={m[key]:.2f} below the required {need}x "
                   f"(snapshot cache not engaging?)")
wire = floor.get("wire")
if wire:
    # ISSUE 20: the TKW1 codec point — encode/decode ceilings (a
    # complexity blow-up guard) and the frame-vs-JSON size floor on
    # the fleet-shaped upsert wave
    for key, cap in (("wire_encode_us", wire["encode_us_max"]),
                     ("wire_decode_us", wire["decode_us_max"])):
        if m[key] > cap * floor["allowed_regression"]:
            bad.append(f"{key}={m[key]:.0f}us exceeds ceiling {cap}us "
                       f"x {floor['allowed_regression']}")
    if m["wire_ratio"] < wire["micro_ratio_min"]:
        bad.append(f"wire_ratio={m['wire_ratio']:.2f} below the "
                   f"required {wire['micro_ratio_min']}x (table "
                   f"encoding / interning / compression not engaging?)")
if "lint_wall_s_floor" in floor:
    # the CFG dataflow passes must not blow up lint wall time — the
    # static analysis runs on every tier-1 invocation
    ls = bench.lint_stats()
    print(json.dumps({"lint_wall_s": ls["wall_s"],
                      "lint_findings": ls["findings"]}))
    limit = floor["lint_wall_s_floor"] * floor["allowed_regression"]
    if ls["wall_s"] > limit:
        bad.append(f"lint wall {ls['wall_s']:.2f}s exceeds floor "
                   f"{floor['lint_wall_s_floor']}s "
                   f"x {floor['allowed_regression']}")
if bad:
    sys.exit("perf smoke FAILED: " + "; ".join(bad))
print("perf smoke OK")
PY

echo
echo "== kilonode smoke (scenario 10: 1024 nodes, batched cycles +"
echo "   fake clock; deterministic trace — throughput floors from"
echo "   tools/perf_floor.json) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["kilonode"]
os.environ.setdefault("TPUKUBE_KILONODE_PODS", str(floor["pods"]))

from tpukube.sim import scenarios

# the scenario itself raises on invariant violations (gang uncommitted,
# ledger divergence, pod shortfall); the floors below catch perf rot
r = scenarios.run(10)
print(json.dumps({
    "pods_total": r["pods_total"], "wall_s": r["wall_s"],
    "pods_per_sec": r["pods_per_sec"],
    "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
    "plan_hit_ratio": r["cycle"]["plan_hit_ratio"],
    "webhook_p99_ms": r["webhook_p99_ms"],
    "time_compression": r["time_compression"],
}))
bad = []
if r["pods_per_sec"] < floor["pods_per_sec_min"]:
    bad.append(f"pods_per_sec={r['pods_per_sec']} below the "
               f"{floor['pods_per_sec_min']}/s floor")
if r["cycle"]["plan_ms_per_pod"] > floor["plan_ms_per_pod_max"]:
    bad.append(f"plan_ms_per_pod={r['cycle']['plan_ms_per_pod']} exceeds "
               f"the {floor['plan_ms_per_pod_max']}ms ceiling")
if bad:
    sys.exit("kilonode smoke FAILED: " + "; ".join(bad))
print("kilonode smoke OK")
PY

echo
echo "== kilonode-10k smoke (scenario 12: 10240 nodes / 40960 chips,"
echo "   incremental snapshot deltas + persistent fast state + batched"
echo "   gang planning; deterministic trace — floors from"
echo "   tools/perf_floor.json) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import sys

floors = json.load(open("tools/perf_floor.json"))
floor = floors["kilonode10k"]
os.environ.setdefault("TPUKUBE_KILONODE10K_PODS", str(floor["pods"]))

from tpukube.sim import scenarios

# the scenario itself raises on invariant violations (gang uncommitted,
# ledger divergence, leaked reservations, pod shortfall); the floors
# below catch perf rot in the ISSUE 10 hot path
r = scenarios.run(12)
print(json.dumps({
    "pods_total": r["pods_total"], "wall_s": r["wall_s"],
    "pods_per_sec": r["pods_per_sec"],
    "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
    "plan_hit_ratio": r["cycle"]["plan_hit_ratio"],
    "fast_patches": r["cycle"]["fast_patches"],
    "gang_batches": r["cycle"]["gang_batches"],
    "snapshot": r["snapshot"],
    "resync": r["resync"],
}))
bad = []
# generation-based incremental resync (ISSUE 15): every churn-wave
# lifecycle reconcile after the one bootstrap full read must ride the
# allocs_since change log — a ratio under the floor means per-wave
# full-ledger reads came back
ratio = r["resync"]["incremental_hit_ratio"]
ratio_min = floors["coldstart"]["resync_hit_ratio_min"]
if ratio is None or ratio < ratio_min:
    bad.append(f"resync incremental_hit_ratio={ratio} below the "
               f"{ratio_min} floor")
if r["pods_per_sec"] < floor["pods_per_sec_min"]:
    bad.append(f"pods_per_sec={r['pods_per_sec']} below the "
               f"{floor['pods_per_sec_min']}/s floor")
if r["cycle"]["plan_ms_per_pod"] > floor["plan_ms_per_pod_max"]:
    bad.append(f"plan_ms_per_pod={r['cycle']['plan_ms_per_pod']} exceeds "
               f"the {floor['plan_ms_per_pod_max']}ms ceiling")
speedup = r["snapshot"]["delta_speedup"]
if speedup is None or speedup < floor["delta_speedup_min"]:
    bad.append(f"delta_speedup={speedup} below the "
               f"{floor['delta_speedup_min']}x floor (the O(delta) "
               f"advance is not beating the forced full rebuild)")
if bad:
    sys.exit("kilonode-10k smoke FAILED: " + "; ".join(bad))
print("kilonode-10k smoke OK")
PY

echo
echo "== cold-start smoke (bulk fleet ingestion at the 10,240-node point:"
echo "   bulk upsert_nodes vs the per-node decision loop — speedup floor"
echo "   from tools/perf_floor.json; the >=5x ISSUE 15 acceptance point"
echo "   is the 102,400-node sweep recorded by the full bench's"
echo "   coldstart key) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import sys

floor = json.load(open("tools/perf_floor.json"))["coldstart"]

import bench

# parity is the test suite's job (tests/test_ingest.py); this stage
# guards the COST model — the probe-validated lazy batch must keep
# beating the per-node decision loop on a cold fleet
r = bench._coldstart_point(floor["nodes"], hetero=False)
print(json.dumps(r))
if r["speedup"] is None or r["speedup"] < floor["ingest_speedup_min"]:
    sys.exit(f"cold-start smoke FAILED: ingest speedup {r['speedup']}x "
             f"below the {floor['ingest_speedup_min']}x floor")
print("cold-start smoke OK")
PY

echo
echo "== decisions smoke (scenario 12 slice with decision provenance at"
echo "   sampling 1.0 — the measured record overhead must stay under the"
echo "   tools/perf_floor.json decisions.overhead_pct_max floor) =="
JAX_PLATFORMS=cpu TPUKUBE_DECISIONS_ENABLED=1 \
  TPUKUBE_DECISIONS_SAMPLE_RATE=1.0 python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["decisions"]
os.environ["TPUKUBE_KILONODE10K_PODS"] = str(floor["pods"])

from tpukube.sim import scenarios

r = scenarios.run(12)
d = r["decisions"]
print(json.dumps({
    "recorded": d["recorded"], "pods": d["pods"],
    "record_seconds": d["record_seconds"],
    "overhead_pct": d["overhead_pct"], "wall_s": r["wall_s"],
}))
bad = []
if not d["recorded"]:
    bad.append("provenance recorded nothing at sampling 1.0")
if d["overhead_pct"] is None or d["overhead_pct"] > floor["overhead_pct_max"]:
    bad.append(f"overhead_pct={d['overhead_pct']} exceeds the "
               f"{floor['overhead_pct_max']}% ceiling")
if bad:
    sys.exit("decisions smoke FAILED: " + "; ".join(bad))
print("decisions smoke OK")
PY

echo
echo "== multitenant smoke (scenario 11: diurnal tenant waves + DRF"
echo "   fairness + SLO-burn shedding under scenario-8 chaos; fixed"
echo "   seed + fixed fault schedule — floors from tools/perf_floor.json) =="
JAX_PLATFORMS=cpu TPUKUBE_CHAOS_SEED=1337 python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["tenancy"]
os.environ.setdefault("TPUKUBE_TENANCY_WAVES", str(floor["waves"]))

from tpukube.sim import scenarios

# the scenario itself raises on policy violations (tenant over quota,
# share ratio > 2, lost gang commit, unjournaled sheds, leaks, ledger
# divergence); the floors below catch throughput/latency rot
r = scenarios.run(11)
print(json.dumps({
    "pods_placed": r["pods_placed"], "wall_s": r["wall_s"],
    "share_ratio_max": r["value"],
    "sheds": sum(r["sheds_by_tenant"].values()),
    "quota_denials": sum(r["quota_denials_by_tenant"].values()),
    "preemptions": r["preemptions"],
    "steady_utilization_min_percent":
        r["steady_utilization_min_percent"],
}))
bad = []
if r["pods_placed"] < floor["pods_placed_min"]:
    bad.append(f"pods_placed={r['pods_placed']} below the "
               f"{floor['pods_placed_min']} floor")
if r["wall_s"] > floor["wall_s_max"]:
    bad.append(f"wall_s={r['wall_s']} exceeds the "
               f"{floor['wall_s_max']}s ceiling")
if bad:
    sys.exit("multitenant smoke FAILED: " + "; ".join(bad))
print("multitenant smoke OK")
PY

echo
echo "== crash-recovery smoke (scenario 13: crash-at-every-seam chaos"
echo "   storm over the durable journal — >=8 crash/restart cycles under"
echo "   the scenario-8 apiserver storm at snapshot_audit_rate=1.0, then"
echo "   the 1024-node checkpoint-warm vs cold restart measurement;"
echo "   floors from tools/perf_floor.json) =="
JAX_PLATFORMS=cpu TPUKUBE_CHAOS_SEED=1337 TPUKUBE_SNAPSHOT_AUDIT_RATE=1.0 \
  python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["recovery"]
os.environ.setdefault("TPUKUBE_CRASH_CYCLES", str(floor["crash_cycles"]))

import bench
from tpukube.sim import scenarios

# the scenario itself raises on invariant violations (lost committed
# gang, ledger divergence, leaked reservations, audit divergence,
# unbounded recovery); the floors below catch recovery-latency rot
r = scenarios.run(13)
print(json.dumps({
    "crash_cycles": r["crash_cycles"], "seams": r["seams"],
    "recovery_modes": r["recovery_modes"],
    "recovery_s_max": r["recovery_s_max"],
    "audit": r["snapshot_audit"], "wall_s": r["wall_s"],
}))
bad = []
if r["recovery_s_max"] > floor["recovery_s_max"]:
    bad.append(f"recovery_s_max={r['recovery_s_max']} exceeds the "
               f"{floor['recovery_s_max']}s ceiling")
if r["snapshot_audit"]["checks"] < 1:
    bad.append("the audit sentinel never checked a recovered snapshot")
# the warm-vs-cold floor runs at the fast 1024-node bench point (the
# 10240-node >=10x acceptance number is recorded by the full bench)
m = bench.recovery(nodes=("1024",))["1024"]
print(json.dumps({"recovery_1024": m}))
if m["replay_speedup"] < floor["replay_speedup_min"]:
    bad.append(f"replay_speedup={m['replay_speedup']} below the "
               f"{floor['replay_speedup_min']}x floor (checkpoint-warm "
               f"restart is not beating the cold rebuild)")
if m["warm_mode"] != "warm" or not m["warm_from_checkpoint"]:
    bad.append(f"bench recovery did not run checkpoint-warm "
               f"(mode={m['warm_mode']})")
if bad:
    sys.exit("crash-recovery smoke FAILED: " + "; ".join(bad))
print("crash-recovery smoke OK")
PY

echo
echo "== shard smoke (scenario 14 at smoke scale: 4 slices / 1024"
echo "   nodes behind 2 planner replicas + plan-served filter answers;"
echo "   zero leaks + both replicas alive enforced by the scenario,"
echo "   throughput floor from tools/perf_floor.json) =="
JAX_PLATFORMS=cpu TPUKUBE_SHARD_SLICES=4 TPUKUBE_SIM_MESH_DIMS=8,8,16 \
  TPUKUBE_PLANNER_REPLICAS=2 python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["shard"]
os.environ.setdefault("TPUKUBE_KILONODE100K_PODS", str(floor["pods"]))

from tpukube.sim import scenarios

# the scenario itself raises on invariant violations (gang uncommitted,
# ledger divergence, leaked reservations, dead replica, pod shortfall)
r = scenarios.run(14)
print(json.dumps({
    "pods_total": r["pods_total"], "wall_s": r["wall_s"],
    "setup_s": r.get("setup_s"),
    "pods_per_sec": r["pods_per_sec"],
    "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
    "replicas": [x["replica"] for x in r["shard"]["replicas"]],
    "slice_assignment": r["shard"]["slice_assignment"],
}))
bad = []
if r["pods_per_sec"] < floor["pods_per_sec_min"]:
    bad.append(f"pods_per_sec={r['pods_per_sec']} below the "
               f"{floor['pods_per_sec_min']}/s floor")
if len(r["shard"]["replicas"]) != 2:
    bad.append("expected 2 planner replicas")
if bad:
    sys.exit("shard smoke FAILED: " + "; ".join(bad))
print("shard smoke OK")
PY

echo
echo "== process-mode shard smoke (scenario-14 smoke fleet behind 2"
echo "   SUBPROCESS planner daemons — true multi-core plane: one"
echo "   worker process per replica, async webhook fan-out; aggregate"
echo "   throughput + parallel-efficiency floors from"
echo "   tools/perf_floor.json; skips where subprocesses are"
echo "   unavailable) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["shard_mp"]

# probe: can this environment spawn worker daemons at all? (some CI
# sandboxes forbid subprocess/socket use — skip LOUDLY, not silently)
from tpukube.core.config import load_config
from tpukube.sched.shard import ShardError, SubprocessTransport

try:
    probe = SubprocessTransport(0, load_config(env={}),
                                fake_clock=False)
    probe.close()
except (ShardError, OSError) as e:
    print(f"process-mode shard smoke SKIPPED: cannot spawn worker "
          f"daemons here ({e})")
    sys.exit(0)

from tpukube.core.mesh import MeshSpec
from tpukube.sim import scenarios

def run_point(n: int) -> dict:
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,16",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "2048",
        "TPUKUBE_FILTER_FROM_PLAN": "1",
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
    })
    mesh = cfg.sim_mesh()
    slices = {
        f"s{i:02d}": MeshSpec(dims=mesh.dims,
                              host_block=mesh.host_block,
                              torus=mesh.torus)
        for i in range(4)
    }
    # the scenario machinery raises on leaks/divergence/shortfall; a
    # fixed trace keeps the smoke deterministic
    return scenarios._kilonode_drive(
        cfg, metric=f"shard_mp_n{n}", total_target=floor["pods"],
        gang_size=128, max_alive=2048, check_leaks=True,
        slices=slices, include_setup=False,
    )

cpus = os.cpu_count() or 1
r1 = run_point(1)
r2 = run_point(2)
eff = (r2["pods_per_sec"] / r1["pods_per_sec"]) / 2 \
    if r1["pods_per_sec"] else 0.0
print(json.dumps({
    "cpus": cpus,
    "n1_pods_per_sec": r1["pods_per_sec"],
    "n2_pods_per_sec": r2["pods_per_sec"],
    "parallel_efficiency": round(eff, 3),
    "n2_transport": r2["shard"]["transport"]["mode"],
}))
bad = []
if r2["pods_per_sec"] < floor["pods_per_sec_min"]:
    bad.append(f"n2 pods_per_sec={r2['pods_per_sec']} below the "
               f"{floor['pods_per_sec_min']}/s floor")
if cpus >= 3:
    # 2 workers + the router need 3 schedulable cores before the
    # efficiency number measures parallelism rather than time-slicing
    if eff < floor["parallel_efficiency_min"]:
        bad.append(f"parallel_efficiency={eff:.3f} below the "
                   f"{floor['parallel_efficiency_min']} floor (the "
                   f"subprocess fan-out is not buying real cores)")
else:
    print(f"parallel-efficiency floor SKIPPED: {cpus} schedulable "
          f"CPU(s) — workers time-slice, the ratio measures "
          f"contention, not parallelism")
if bad:
    sys.exit("process-mode shard smoke FAILED: " + "; ".join(bad))
print("process-mode shard smoke OK")
PY

echo
echo "== wire-codec smoke (ISSUE 20: 2 SUBPROCESS planner daemons —"
echo "   a fixed mixed workload must place bit-identically with"
echo "   wire_codec json vs binary, and a fixed-trace scenario-12"
echo "   slice drive at snapshot_audit_rate=1.0 must move at least"
echo "   bytes_per_wave_ratio_min x fewer bytes/wave over TKW1 than"
echo "   JSON (floors from tools/perf_floor.json); skips where"
echo "   subprocesses are unavailable) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import sys

floor = json.load(open("tools/perf_floor.json"))["wire"]

from tpukube.core.config import load_config
from tpukube.sched.shard import ShardError, SubprocessTransport

try:
    probe = SubprocessTransport(0, load_config(env={}),
                                fake_clock=False)
    probe.close()
except (ShardError, OSError) as e:
    print(f"wire-codec smoke SKIPPED: cannot spawn worker "
          f"daemons here ({e})")
    sys.exit(0)

from tpukube.core.clock import FakeClock
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sim.harness import SimCluster

def cfg_for(codec: str):
    return load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_WIRE_CODEC": codec,
        "TPUKUBE_WIRE_COMPRESS_MIN_BYTES": "256",
    })

def mixed(codec: str):
    """A fixed mixed workload (solo/multi-chip/gang/churn) through the
    per-pod webhook protocol: pod -> (node, sorted device ids)."""
    slices = {sid: MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                            torus=(False, False, False))
              for sid in ("s0", "s1")}
    out = {}
    with SimCluster(cfg_for(codec), clock=FakeClock(),
                    in_process=True, slices=slices) as c:
        def put(pod):
            node, alloc = c.schedule(pod)
            out[alloc.pod_key] = (node,
                                  tuple(sorted(alloc.device_ids)))
        put(c.make_pod("solo-0", tpu=1))
        put(c.make_pod("multi-0", tpu=2))
        grp = PodGroup("pg", min_member=2)
        for i in range(2):
            put(c.make_pod(f"pg-{i}", tpu=1, group=grp, priority=10))
        c.complete_pod("solo-0")
        put(c.make_pod("solo-1", tpu=1))
        snap = c.extender.wire_totals()
    return out, snap

placed_json, wire_json_small = mixed("json")
placed_bin, wire_bin_small = mixed("binary")
bad = []
if placed_json != placed_bin:
    diff = {k for k in placed_json.keys() | placed_bin.keys()
            if placed_json.get(k) != placed_bin.get(k)}
    bad.append(f"codec-on placements diverge from codec-off: {sorted(diff)}")
if wire_bin_small.get("codec") != "binary":
    bad.append("binary run moved no TKW1 frames (negotiation broken?)")

# the byte bill at drive scale: the same fixed trace once per codec
from tpukube.sim import scenarios

def drive(codec: str):
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,16",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "2048",
        "TPUKUBE_FILTER_FROM_PLAN": "1",
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0",
        "TPUKUBE_WIRE_CODEC": codec,
    })
    mesh = cfg.sim_mesh()
    slices = {
        f"s{i:02d}": MeshSpec(dims=mesh.dims,
                              host_block=mesh.host_block,
                              torus=mesh.torus)
        for i in range(4)
    }
    return scenarios._kilonode_drive(
        cfg, metric=f"wire_{codec}", total_target=floor["pods"],
        gang_size=128, max_alive=2048, check_leaks=True,
        slices=slices, include_setup=False,
    )

wj = drive("json")["wire"]
wb = drive("binary")["wire"]
ratio = (wj["bytes_per_wave"] / wb["bytes_per_wave"]
         if wb["bytes_per_wave"] else 0.0)
print(json.dumps({
    "json_bytes_per_wave": wj["bytes_per_wave"],
    "binary_bytes_per_wave": wb["bytes_per_wave"],
    "bytes_per_wave_ratio": round(ratio, 2),
    "binary_compress_ratio": wb.get("compress_ratio"),
    "binary_saved_bytes": wb.get("saved_bytes"),
}))
if wb.get("codec") != "binary":
    bad.append("binary drive recorded no codec (negotiation broken?)")
if ratio < floor["bytes_per_wave_ratio_min"]:
    bad.append(f"bytes/wave ratio {ratio:.2f} below the "
               f"{floor['bytes_per_wave_ratio_min']}x floor")
if bad:
    sys.exit("wire-codec smoke FAILED: " + "; ".join(bad))
print("wire-codec smoke OK")
PY

echo
echo "== federated observability smoke (2 SUBPROCESS planner daemons:"
echo "   the router's merged /metrics must lint clean over HTTP with"
echo "   replica attribution, the stitched /explain must answer a DCN"
echo "   gang member citing both replicas, and the router-side"
echo "   provenance overhead on a sharded scenario-12 drive stays under"
echo "   the tools/perf_floor.json ceiling; skips where subprocesses"
echo "   are unavailable) =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import socket
import sys
import urllib.request

floor = json.load(open("tools/perf_floor.json"))["federated_obs"]

# probe: can this environment spawn worker daemons at all? (some CI
# sandboxes forbid subprocess/socket use — skip LOUDLY, not silently)
from tpukube.core.config import load_config
from tpukube.sched.shard import ShardError, SubprocessTransport

try:
    probe = SubprocessTransport(0, load_config(env={}),
                                fake_clock=False)
    probe.close()
except (ShardError, OSError) as e:
    print(f"federated observability smoke SKIPPED: cannot spawn "
          f"worker daemons here ({e})")
    sys.exit(0)

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.obs.slo import validate_exposition
from tpukube.sched.extender import run_probe_server
from tpukube.sched.shardworker import make_router_app
from tpukube.sim import scenarios
from tpukube.sim.harness import SimCluster

bad = []

# part 1: the live federated plane — fill both slices, force a DCN
# rendezvous, then read the router's observability listener over HTTP
cfg = load_config(env={
    "TPUKUBE_PLANNER_REPLICAS": "2",
    "TPUKUBE_SHARD_TRANSPORT": "subprocess",
    "TPUKUBE_BATCH_ENABLED": "1",
    "TPUKUBE_DECISIONS_ENABLED": "1",
    "TPUKUBE_DECISIONS_SAMPLE_RATE": "1.0",
})
slices = {
    sid: MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                  torus=(False, False, False))
    for sid in ("s0", "s1")
}
with SimCluster(cfg, in_process=True, slices=slices) as c:
    for g in ("fill-a", "fill-b"):
        grp = PodGroup(g, min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"{g}-{i}", tpu=1, group=grp))
    dcn = PodGroup("dcn", min_member=8, allow_dcn=True)
    for i in range(8):
        c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=dcn,
                              priority=50))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stop = run_probe_server(make_router_app(c.extender),
                            "127.0.0.1", port)
    try:
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        errors = validate_exposition(text)
        if errors:
            bad.append(f"federated /metrics fails promlint: {errors}")
        for rep in ('replica="r0"', 'replica="r1"'):
            if rep not in text:
                bad.append(f"federated /metrics misses {rep}")
        if "tpukube_router_wire_bytes_total" not in text:
            bad.append("federated /metrics misses the wire counter")
        with urllib.request.urlopen(
                f"{base}/explain?pod=default/dcn-0", timeout=10) as r:
            doc = json.load(r)
        why = "\n".join(doc.get("why", []))
        if doc.get("verdict") != "placed":
            bad.append(f"stitched explain verdict={doc.get('verdict')}")
        if "DCN rendezvous committed" not in why \
                or "replica r0" not in why or "replica r1" not in why:
            bad.append("stitched explain does not cite both replicas "
                       "and the rendezvous verdict")
    finally:
        stop()

# part 2: observability overhead on the sharded drive — the router's
# DecisionLog (route/spillover/rendezvous stages + fan-out spans)
# against real subprocess RPCs; same measurement as the decisions
# smoke, taken on the federated plane
cfg = load_config(env={
    "TPUKUBE_SIM_MESH_DIMS": "8,8,16",
    "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    "TPUKUBE_BATCH_ENABLED": "1",
    "TPUKUBE_BATCH_MAX_PODS": "2048",
    "TPUKUBE_FILTER_FROM_PLAN": "1",
    "TPUKUBE_PLANNER_REPLICAS": "2",
    "TPUKUBE_SHARD_TRANSPORT": "subprocess",
    "TPUKUBE_DECISIONS_ENABLED": "1",
    "TPUKUBE_DECISIONS_SAMPLE_RATE": "1.0",
})
mesh = cfg.sim_mesh()
slices = {
    f"s{i:02d}": MeshSpec(dims=mesh.dims, host_block=mesh.host_block,
                          torus=mesh.torus)
    for i in range(4)
}
r = scenarios._kilonode_drive(
    cfg, metric="federated_obs", total_target=floor["pods"],
    gang_size=128, max_alive=2048, check_leaks=True,
    slices=slices, include_setup=False,
)
print(json.dumps({
    "pods": r["pods_total"],
    "overhead_pct": r["decisions"]["overhead_pct"],
    "wire_total_bytes": r["wire"]["total_bytes"],
    "wire_bytes_per_wave": r["wire"]["bytes_per_wave"],
}))
if r["decisions"]["overhead_pct"] > floor["overhead_pct_max"]:
    bad.append(f"router provenance overhead "
               f"{r['decisions']['overhead_pct']}% above the "
               f"{floor['overhead_pct_max']}% ceiling")
if not r["wire"]["total_bytes"]:
    bad.append("sharded drive billed zero wire bytes")
if bad:
    sys.exit("federated observability smoke FAILED: " + "; ".join(bad))
print("federated observability smoke OK")
PY

echo
echo "== capacity smoke (flight recorder on a scenario-12 slice at"
echo "   sample-interval 1 — measured overhead under the"
echo "   tools/perf_floor.json capacity.overhead_pct_max ceiling; then"
echo "   stranded-demand forensics federated across 2 SUBPROCESS"
echo "   planner daemons: a deliberately fragmented 64-chip gang must"
echo "   classify 'fragmented' with recoverable chips and per-replica"
echo "   attribution, and the what-if probe must confirm no contiguous"
echo "   fit while free chips cover the ask; the federated half skips"
echo "   where subprocesses are unavailable) =="
JAX_PLATFORMS=cpu TPUKUBE_CAPACITY_ENABLED=1 \
  TPUKUBE_CAPACITY_SAMPLE_INTERVAL_SECONDS=1 python - <<'PY'
import json
import os
import sys

floor = json.load(open("tools/perf_floor.json"))["capacity"]
os.environ["TPUKUBE_KILONODE10K_PODS"] = str(floor["pods"])

from tpukube.sim import scenarios

r = scenarios.run(12)
cap = r["capacity"]
print(json.dumps({
    "samples": cap["samples"], "sample_seconds": cap["sample_seconds"],
    "overhead_pct": cap["overhead_pct"], "wall_s": r["wall_s"],
    "stranded_chips": r["stranded"]["chips_requested"],
}))
bad = []
if not cap["samples"]:
    bad.append("the flight recorder took no samples at interval 1")
if not r.get("utilization_over_time"):
    bad.append("scenario 12 recorded no utilization_over_time")
if cap["overhead_pct"] is None \
        or cap["overhead_pct"] > floor["overhead_pct_max"]:
    bad.append(f"recorder overhead_pct={cap['overhead_pct']} exceeds "
               f"the {floor['overhead_pct_max']}% ceiling")
if bad:
    sys.exit("capacity smoke FAILED: " + "; ".join(bad))
print("capacity recorder-overhead smoke OK")
PY

JAX_PLATFORMS=cpu python - <<'PY'
import contextlib
import io
import json
import socket
import sys
import urllib.request

from tpukube.core.config import load_config
from tpukube.sched.shard import ShardError, SubprocessTransport

try:
    probe = SubprocessTransport(0, load_config(env={}),
                                fake_clock=False)
    probe.close()
except (ShardError, OSError) as e:
    print(f"capacity forensics smoke SKIPPED: cannot spawn worker "
          f"daemons here ({e})")
    sys.exit(0)

from tpukube.core import codec
from tpukube.core.clock import FakeClock
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sched.extender import run_probe_server
from tpukube.sched.shardworker import make_router_app
from tpukube.sim.harness import SimCluster

bad = []
cfg = load_config(env={
    "TPUKUBE_PLANNER_REPLICAS": "2",
    "TPUKUBE_SHARD_TRANSPORT": "subprocess",
    "TPUKUBE_BATCH_ENABLED": "1",
    "TPUKUBE_CAPACITY_ENABLED": "1",
    "TPUKUBE_CAPACITY_SAMPLE_INTERVAL_SECONDS": "1",
})
# one 8x8x2 slice (128 chips) per replica
slices = {
    sid: MeshSpec(dims=(8, 8, 2), host_block=(2, 2, 1),
                  torus=(False, False, False))
    for sid in ("s0", "s1")
}
with SimCluster(cfg, in_process=True, slices=slices,
                clock=FakeClock()) as c:
    # fill the fleet with 1-chip pods, then complete every pod on an
    # even x-plane: each slice keeps 64 chips free but fragmented into
    # 16-chip planes — the ROADMAP defrag scenario's precondition
    for i in range(256):
        c.schedule(c.make_pod(f"fill-{i}", tpu=1))
    for key, pod in list(c.pods.items()):
        alloc = codec.decode_alloc(
            pod["metadata"]["annotations"][codec.ANNO_ALLOC])
        if alloc.coords and alloc.coords[0][0] % 2 == 0:
            c.pods.pop(key)
    c._lifecycle.check_once()
    c.advance(2.0)
    # a 64-chip gang: chips are free (64/slice) but no contiguous box
    grp = PodGroup("stranded", min_member=64)
    try:
        c.schedule(c.make_pod("stranded-0", tpu=1, group=grp))
        bad.append("the fragmented 64-chip gang unexpectedly placed")
    except Exception:
        pass
    c.advance(2.0)

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stop = run_probe_server(make_router_app(c.extender),
                            "127.0.0.1", port)
    try:
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/capacity",
                                    timeout=10) as r:
            doc = json.load(r)
        rows = {row["shape"]: row
                for row in doc["stranded"]["by_shape"]}
        row = rows.get("64")
        if row is None:
            bad.append(f"no stranded ledger row for the 64-chip "
                       f"demand: {doc['stranded']}")
        else:
            if not row["reasons"].get("fragmented"):
                bad.append(f"root cause is not fragmented: "
                           f"{row['reasons']}")
            if not any(rep in row.get("replicas", {})
                       for rep in ("r0", "r1")):
                bad.append("stranded row carries no per-replica "
                           "attribution")
        if doc["stranded"]["recoverable_chips"] <= 0:
            bad.append("fragmented stranding reports no "
                       "repack-recoverable chips")
        if not doc["unschedulable"].get("fragmented"):
            bad.append(f"tpukube_unschedulable_pods misses the "
                       f"fragmented count: {doc['unschedulable']}")
        missing = [rep for rep in ("r0", "r1")
                   if rep not in doc["stats"]]
        if missing:
            bad.append(f"federated /capacity misses replicas "
                       f"{missing}")
        if doc["dead_replicas"]:
            bad.append(f"live replicas reported dead: "
                       f"{doc['dead_replicas']}")
        with urllib.request.urlopen(
                f"{base}/capacity/probe?count=64", timeout=10) as r:
            probe_doc = json.load(r)
        if probe_doc["fits"]:
            bad.append("the what-if probe claims a contiguous "
                       "64-chip fit on a fragmented fleet")
        if probe_doc["free_chips"] < 64:
            bad.append(f"probe sees {probe_doc['free_chips']} free "
                       f"chips — the fragmentation proof needs >= 64")
        # the CLI against the live federated endpoint: the sparkline
        # rendering must name the stranded shape and the root cause
        from tpukube.cli import main_obs
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            main_obs(["capacity", "--url", base])
        text = out.getvalue()
        if "64-chip" not in text or "fragmented" not in text:
            bad.append(f"tpukube-obs capacity does not name the "
                       f"stranded shape + cause:\n{text}")
        print(json.dumps({
            "stranded": doc["stranded"],
            "unschedulable": doc["unschedulable"],
            "probe_fits": probe_doc["fits"],
            "probe_free_chips": probe_doc["free_chips"],
        }))
    finally:
        stop()
if bad:
    sys.exit("capacity forensics smoke FAILED: " + "; ".join(bad))
print("capacity forensics smoke OK")
PY

echo
echo "== native asan (libtpuinfo self-test under ASan/UBSan) =="
if command -v g++ >/dev/null 2>&1; then
  make -C tpukube/native asan
else
  echo "skipped: no C++ toolchain on this machine"
fi

echo
echo "check.sh: all stages passed"

"""ISSUE 14: process-parallel sharded control plane — one planner
daemon per replica behind the async webhook router.

The acceptance gates covered here:
  * process-mode N=1 placements identical to the in-process router on
    mixed workloads (whole-chip, multi-chip, vTPU, gangs, preemption);
  * replica-daemon kill mid-rendezvous-commit over the REAL transport
    (janitor all-or-nothing death still holds, leak-free convergence);
  * health-check-driven dead-marking + warm restart of a killed worker
    process;
  * config validation for the new knobs;
plus the satellites:
  * incremental unhealthy/broken/share-count ledger caches property-
    tested against the ground-truth walks across the full lifecycle;
  * the harness's NodesCached sampled-webhook bodies parity-checked
    against the protocol-faithful names body.

Worker daemons are real subprocesses; tests that need them skip
gracefully where spawning is unavailable.
"""

from __future__ import annotations

import random

import pytest

from tpukube.chaos import leaked_reservations, ledger_divergence
from tpukube.core import codec
from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sim.harness import SimCluster


def can_spawn_workers() -> bool:
    from tpukube.sched.shard import ShardError, SubprocessTransport

    try:
        probe = SubprocessTransport(0, load_config(env={}),
                                    fake_clock=False)
        probe.close()
        return True
    except (ShardError, OSError):
        return False


needs_workers = pytest.mark.skipif(
    not can_spawn_workers(),
    reason="cannot spawn shard-worker subprocesses here",
)


def proc_config(n: int, **extra: str):
    return load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_BATCH_ENABLED": "1",
        **extra,
    })


def two_slices(dims=(2, 2, 2)) -> dict[str, MeshSpec]:
    return {
        sid: MeshSpec(dims=dims, host_block=(2, 2, 1),
                      torus=(False, False, False))
        for sid in ("s0", "s1")
    }


# -- config validation -------------------------------------------------------

def test_config_validation_shard_transport():
    assert load_config(env={}).shard_transport == "inprocess"
    cfg = load_config(env={"TPUKUBE_SHARD_TRANSPORT": "subprocess"})
    assert cfg.shard_transport == "subprocess"
    with pytest.raises(ValueError, match="shard_transport"):
        load_config(env={"TPUKUBE_SHARD_TRANSPORT": "carrier-pigeon"})


def test_pod_to_k8s_roundtrip():
    """The subprocess transport ships PodInfo as v1.Pod dicts; the
    round-trip through pod_from_k8s must preserve everything the
    planner reasons on — including the gang group, which rides the
    annotations."""
    from tpukube.sched import kube

    grp = PodGroup("rt-gang", min_member=4, allow_dcn=True)
    pod = kube.pod_from_k8s({
        "metadata": {"name": "rt", "namespace": "ns1", "uid": "u-9",
                     "annotations": codec.pod_group_annotations(grp),
                     "labels": {"team": "a"}},
        "spec": {"priority": 7, "containers": [
            {"name": "main",
             "resources": {"requests": {"qiniu.com/tpu": "2"}}},
        ]},
    })
    back = kube.pod_from_k8s(kube.pod_to_k8s(pod))
    assert back.key() == pod.key()
    assert back.uid == pod.uid
    assert back.priority == pod.priority
    assert back.labels == pod.labels
    assert back.requests() == pod.requests()
    assert back.group is not None
    assert (back.group.name, back.group.min_member,
            back.group.allow_dcn) == ("rt-gang", 4, True)


# -- process-mode N=1 placement parity ---------------------------------------

def _mixed_workload(c: SimCluster) -> dict[str, tuple[str, tuple]]:
    """Drive the mixed workload through the per-pod webhook protocol
    and return pod key -> (node, sorted device ids)."""
    placements: dict[str, tuple[str, tuple]] = {}

    def put(pod):
        node, alloc = c.schedule(pod)
        placements[alloc.pod_key] = (node, tuple(sorted(alloc.device_ids)))

    put(c.make_pod("solo-0", tpu=1))
    put(c.make_pod("multi-0", tpu=2))
    put(c.make_pod("vt-0", vtpu=1))
    grp = PodGroup("pg", min_member=2)
    for i in range(2):
        put(c.make_pod(f"pg-{i}", tpu=1, group=grp, priority=10))
    # fill the rest of the mesh with cheap pods, then preempt with a
    # high-priority gang that needs a contiguous block
    filler = []
    for i in range(8):
        name = f"fill-{i}"
        try:
            put(c.make_pod(name, tpu=1, priority=0))
            filler.append(name)
        except RuntimeError:
            c.pods.pop(f"default/{name}", None)
            break
    pre = PodGroup("pre", min_member=2)
    for i in range(2):
        put(c.make_pod(f"pre-{i}", tpu=1, group=pre, priority=100))
    c.complete_pod("solo-0")
    put(c.make_pod("solo-1", tpu=1))
    return placements


@needs_workers
def test_process_n1_placement_parity():
    """N=1 over the subprocess transport places the mixed workload
    (gangs, preemption, vTPU) exactly as the in-process plane does:
    the transport changes the wire, never the computation."""
    results = {}
    for transport in ("inprocess", "subprocess"):
        cfg = load_config(env={
            "TPUKUBE_PLANNER_REPLICAS": "1",
            "TPUKUBE_SHARD_TRANSPORT": transport,
            "TPUKUBE_BATCH_ENABLED": "1",
        })
        mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1),
                        torus=(False, False, False))
        with SimCluster(cfg, mesh=mesh, vtpu_nodes={"host-1-0-0"},
                        in_process=True) as c:
            results[transport] = _mixed_workload(c)
            assert ledger_divergence(c) == []
    assert results["subprocess"] == results["inprocess"]


@needs_workers
def test_process_batch_driver_and_zero_divergence():
    """The batched driver surface (admit_many / planned_many /
    bind_many) over two worker daemons: every pod lands, ledger and
    store agree, and the per-replica transport telemetry is live."""
    clock = FakeClock()
    with SimCluster(proc_config(2), clock=clock, in_process=True,
                    slices=two_slices()) as c:
        pods = [c.make_pod(f"b{i}", tpu=1) for i in range(12)]
        placed = c.schedule_pending(pods)
        assert len(placed) == 12
        assert ledger_divergence(c) == []
        doc = c.extender.statusz()
        assert doc["transport"]["mode"] == "subprocess"
        assert all(r["requests"] > 0
                   for r in doc["transport"]["replicas"])
        # both shards actually planned work
        assert all(r["allocs"] > 0 for r in doc["replicas"])


@needs_workers
def test_process_release_and_eviction_pull():
    """Worker-side releases (batched through release_many) free chips,
    and a worker-side gang rollback's victims surface on the router's
    shared eviction bus via pull_evictions."""
    clock = FakeClock()
    cfg = proc_config(2)
    with SimCluster(cfg, clock=clock, in_process=True,
                    slices=two_slices()) as c:
        pods = [c.make_pod(f"r{i}", tpu=1) for i in range(8)]
        c.schedule_pending(pods)
        before = c.utilization()
        assert before > 0
        for i in range(8):
            c.pods.pop(f"default/r{i}")
        c._lifecycle.check_once()
        assert c.utilization() == 0.0
        assert ledger_divergence(c) == []
        # half-assemble a gang, then let its TTL expire: the worker's
        # janitor rolls it back and evicts the bound member — which
        # must reach the ROUTER's eviction bus
        grp = PodGroup("half", min_member=8)
        c.schedule(c.make_pod("half-0", tpu=1, group=grp))
        c.advance(cfg.reservation_ttl_seconds + 1)
        c.extender.sweep()
        c.extender.pull_evictions()
        assert "default/half-0" in c.extender.pending_evictions
        c.drain_evictions()
        for _ in range(4):
            c._lifecycle.check_once()
            c.extender.sweep()
            c.extender.pull_evictions()
            c.drain_evictions()
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


# -- replica-daemon death over the real transport ----------------------------

def _span_both_replicas(c: SimCluster) -> None:
    """Commit one 4-member gang into each slice so no single replica
    can hold an 8-chip gang whole — the rendezvous shape (gang routing
    spreads the fillers emptiest-replica-first)."""
    for g in ("fill-a", "fill-b"):
        grp = PodGroup(g, min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"{g}-{i}", tpu=1, group=grp))


@needs_workers
def test_worker_kill_mid_rendezvous_commit_converges():
    """SIGKILL one worker DAEMON after a rendezvous part bound a
    member but before the gang committed: the health check marks the
    replica dead, the janitor dissolves the surviving parts
    all-or-nothing, the plane converges leak-free, and a warm restart
    rebuilds the shard from pod annotations — with every surviving
    replica's snapshot audited against its ledger
    (snapshot_audit_rate=1.0, the acceptance setting)."""
    clock = FakeClock()
    cfg = proc_config(2, TPUKUBE_SNAPSHOT_AUDIT_RATE="1.0")
    with SimCluster(cfg, clock=clock, in_process=True,
                    slices=two_slices()) as c:
        _span_both_replicas(c)
        grp = PodGroup("dcn", min_member=8, allow_dcn=True)
        # bind a few members (not the quorum): rendezvous prepared,
        # parts uncommitted
        for i in range(3):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=grp,
                                  priority=50))
        router = c.extender
        assert ("default", "dcn") in router._dcn
        assert not router._dcn[("default", "dcn")].committed
        # REAL process death: SIGKILL the daemon out from under the
        # router (not a modeled flag — the transport discovers it)
        victim = next(idx for idx, rdv
                      in [(i, None) for i in (0, 1)]
                      if router._dcn[("default", "dcn")]
                      .parts.get(idx) is not None)
        router.replicas[victim].transport._proc.kill()
        router.replicas[victim].transport._proc.wait(timeout=10)
        clock.advance(1.0)
        router.health_check()
        assert router.replicas[victim].killed
        aborted = router.sweep()
        assert ("default", "dcn") in aborted
        # converge: the surviving part's members are evicted (nothing
        # leaks); members bound to the DEAD shard's nodes converge
        # through the restart below, exactly the chaos helper's order
        for _ in range(6):
            c._lifecycle.check_once()
            router.pull_evictions()
            c.drain_evictions()
            router.sweep()
        assert leaked_reservations(c) == []
        # warm restart: fresh daemon, nodes re-ingested, ledger rebuilt
        # — the aborted rendezvous' restored fragment dies
        # all-or-nothing inside restart (the pending sentence)
        restored = c.restart_replica(victim)
        assert router.replicas[victim].alive
        assert restored >= 0
        for _ in range(6):
            c._lifecycle.check_once()
            router.pull_evictions()
            c.drain_evictions()
            router.sweep()
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []
        # the audit sentinel ran over the real transport and found
        # every surviving snapshot faithful to its ledger
        audit = router.audit_stats()
        assert audit["checks"] > 0
        assert audit["divergences"] == 0
        # the restarted shard serves placements again
        pod = c.make_pod("after-restart", tpu=1)
        node, _alloc = c.schedule(pod)
        assert node


@needs_workers
def test_health_check_dead_marking_and_warm_restart():
    """A worker daemon that dies between drives is found by the
    router's health check (crash_replica semantics: excluded from the
    federated views) and a warm restart restores its allocations from
    the pod store."""
    clock = FakeClock()
    with SimCluster(proc_config(2), clock=clock, in_process=True,
                    slices=two_slices()) as c:
        placed = c.schedule_pending(
            [c.make_pod(f"p{i}", tpu=1) for i in range(8)]
        )
        router = c.extender
        victims = {idx for idx in (0, 1)
                   if router.replicas[idx].transport.summary()["allocs"]}
        victim = sorted(victims)[0]
        held = router.replicas[victim].transport.summary()["allocs"]
        router.replicas[victim].transport._proc.kill()
        router.replicas[victim].transport._proc.wait(timeout=10)
        # advance the ROUTER clock only (not the fan-out, which would
        # discover the corpse inline through its own transport error):
        # the next health check must find the dead daemon itself
        clock.advance(1.0)
        assert router.health_check() == 1
        rep = router.replicas[victim]
        assert rep.killed and not rep.alive
        # the corpse's ledger is excluded from the federated view
        assert len(router.state.allocations()) == len(placed) - held
        restored = c.restart_replica(victim)
        assert restored == held
        assert len(router.state.allocations()) == len(placed)
        assert ledger_divergence(c) == []


@needs_workers
def test_transport_failure_marks_dead_inline():
    """A connection failure DURING a call (not just a failed health
    probe) marks the replica dead through on_down — the router routes
    around it without waiting for the next health check."""
    clock = FakeClock()
    with SimCluster(proc_config(2), clock=clock, in_process=True,
                    slices=two_slices()) as c:
        router = c.extender
        router.replicas[1].transport._proc.kill()
        router.replicas[1].transport._proc.wait(timeout=10)
        with pytest.raises(Exception):
            # direct transport call: the failure surfaces AND trips
            # the dead-marking callback
            router.replicas[1].transport.summary()
        assert router.replicas[1].killed
        # the plane still schedules on the survivor
        node, _ = c.schedule(c.make_pod("survivor", tpu=1))
        assert node.startswith("s0-") or node.startswith("s1-")


# -- fan-out concurrency ------------------------------------------------------

@needs_workers
def test_fan_out_overlaps_across_replicas():
    """Calls to DISTINCT replicas genuinely overlap in time (the
    multi-core lever): two workers each advancing a FakeClock while
    the router fans out must finish in roughly one round-trip, not
    two. Wall-clock based but I/O-bound, so it holds on any machine —
    including single-core CI, where CPU-bound scaling cannot show."""
    import time as time_mod

    clock = FakeClock()
    with SimCluster(proc_config(2), clock=clock, in_process=True,
                    slices=two_slices()) as c:
        router = c.extender
        # warm the connections
        router._fan_out(router.replicas,
                        lambda rep: rep.transport.healthz())

        slow = 0.3

        def stall(rep):
            # one slow request per replica, through each replica's own
            # ordered connection
            t0 = time_mod.perf_counter()
            rep.transport._request("POST", "/worker/stall",
                                   {"seconds": slow})
            return time_mod.perf_counter() - t0

        t0 = time_mod.perf_counter()
        out = router._fan_out(router.replicas, stall)
        wall = time_mod.perf_counter() - t0
        assert len(out) == 2
        # serial would be >= 2*slow; concurrent ~= slow (+ slack)
        assert wall < 1.7 * slow, f"fan-out serialized: {wall:.3f}s"


# -- satellite: incremental ledger caches vs ground-truth walks ---------------

def test_aux_caches_match_walk_through_lifecycle():
    """unhealthy_coords / broken_links / slice_share_counts served
    from the incremental caches equal the ground-truth walks after
    EVERY mutation across a random lifecycle (commits, releases,
    health flips, link faults, structural re-annotations)."""
    cfg = load_config(env={})
    mesh = MeshSpec(dims=(4, 4, 2), host_block=(2, 2, 1),
                    torus=(False, False, False))
    rng = random.Random(1414)
    with SimCluster(cfg, mesh=mesh, in_process=True) as c:
        st = c.extender.state
        sid = cfg.slice_id

        def check():
            # force-seed through the cached accessors, then compare
            # against the independent walks
            assert st.unhealthy_coords(sid) == \
                st.walk_unhealthy_coords(sid)
            assert st.broken_links(sid) == st.walk_broken_links(sid)
            assert st.slice_share_counts(sid) == \
                st.walk_slice_share_counts(sid)

        c._sync_nodes()
        check()
        alive: list[str] = []
        links = [(c1, c2) for c1 in mesh.all_coords()
                 for c2 in mesh.neighbors(c1) if c1 < c2]
        faulted: list[tuple] = []
        sick: list[tuple[str, int]] = []
        for step in range(60):
            op = rng.random()
            if op < 0.35:
                name = f"pp-{step}"
                try:
                    c.schedule(c.make_pod(name, tpu=1))
                    alive.append(name)
                except RuntimeError:
                    c.pods.pop(f"default/{name}", None)
            elif op < 0.55 and alive:
                c.complete_pod(alive.pop(rng.randrange(len(alive))))
            elif op < 0.7:
                node = rng.choice(sorted(c.nodes))
                chip = rng.randrange(4)
                if (node, chip) in sick:
                    c.inject_fault(node, chip, healthy=True)
                    sick.remove((node, chip))
                else:
                    c.inject_fault(node, chip, healthy=False)
                    sick.append((node, chip))
                c._sync_nodes()
            else:
                if faulted and rng.random() < 0.5:
                    a, b = faulted.pop(rng.randrange(len(faulted)))
                    c.inject_link_fault(a, b, up=True)
                else:
                    a, b = rng.choice(links)
                    c.inject_link_fault(a, b, up=False)
                    if (a, b) not in faulted:
                        faulted.append((a, b))
                c._sync_nodes()
            check()
        assert ledger_divergence(c) == []


def test_aux_caches_unseeded_until_read():
    """The caches stay unseeded until first read (mutation seams on an
    unseeded slice are no-ops, matching _occ_cache's contract)."""
    from tpukube.sched.state import ClusterState

    st = ClusterState()
    assert st._unhealthy_cache == {}
    assert st._broken_cache == {}
    assert st._share_cache == {}


# -- satellite: NodesCached sampled-webhook bodies ---------------------------

def test_nodes_cached_body_parity():
    """The NodesCached webhook body places pods exactly as the
    protocol-faithful names body, on both the plain extender and the
    in-process sharded router — and after the first full send the
    harness's body really is O(1)."""
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1),
                    torus=(False, False, False))

    def run(cached: bool, replicas: int = 1):
        cfg = load_config(env={
            "TPUKUBE_BATCH_ENABLED": "1",
            "TPUKUBE_PLANNER_REPLICAS": str(replicas),
        })
        out = {}
        with SimCluster(cfg, mesh=mesh if replicas == 1 else None,
                        slices=(None if replicas == 1 else {
                            "s0": mesh, "s1": mesh,
                        }),
                        in_process=True,
                        cached_node_body=cached) as c:
            grp = PodGroup("ncg", min_member=2)
            workload = ([("w0", {}), ("w1", {"tpu": 2})]
                        + [(f"g{i}", {"group": grp}) for i in range(2)]
                        + [("w2", {})])
            for name, kw in workload:
                kw = dict(kw)
                kw.setdefault("tpu", 1)
                node, alloc = c.schedule(c.make_pod(name, **kw))
                out[name] = (node, tuple(sorted(alloc.device_ids)))
            if cached:
                args, pending = c._extender_node_args()
                assert pending is None and args == {"NodesCached": True}
            assert ledger_divergence(c) == []
        return out

    assert run(cached=True) == run(cached=False)
    assert run(cached=True, replicas=2) == run(cached=False,
                                               replicas=2)


def test_nodes_cached_body_rejected_without_pod():
    from tpukube.sched import kube

    with pytest.raises(kube.KubeSchemaError):
        kube.parse_extender_args({"NodesCached": True})
    pod, nodes, names = kube.parse_extender_args({
        "Pod": {"metadata": {"name": "x"}, "spec": {}},
        "NodesCached": True,
    })
    assert nodes is None and names is None

"""DCN-spanning gang tests (multislice data-parallel jobs).

A gang normally holds one contiguous box in one ICI slice. With the
``tpu.qiniu.com/pod-group-allow-dcn`` annotation (PodGroup.allow_dcn) a
DP-style job opts in to splitting across slices — one contiguous sub-box
per slice — when no single slice fits. Single-slice placement is always
preferred; the split is the fallback, not the default.
"""

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster

M44 = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))


def _cfg():
    return load_config(env={"TPUKUBE_RESERVATION_TTL_SECONDS": "30"})


def two_slices():
    return SimCluster(_cfg(), slices={"slice-a": M44, "slice-b": M44})


def test_allow_dcn_annotation_roundtrip():
    g = PodGroup("dp", min_member=4, allow_dcn=True)
    annos = codec.pod_group_annotations(g)
    assert annos[codec.ANNO_POD_GROUP_ALLOW_DCN] == "true"
    back = codec.pod_group_from_annotations(annos)
    assert back.allow_dcn is True
    plain = codec.pod_group_from_annotations(
        codec.pod_group_annotations(PodGroup("x", 2))
    )
    assert plain.allow_dcn is False


def test_allow_dcn_rejects_shape_hint():
    annos = codec.pod_group_annotations(PodGroup("dp", 4))
    annos[codec.ANNO_POD_GROUP_ALLOW_DCN] = "true"
    annos[codec.ANNO_POD_GROUP_SHAPE] = "2x2"
    with pytest.raises(codec.CodecError, match="incompatible"):
        codec.pod_group_from_annotations(annos)


def test_dcn_gang_splits_when_no_single_slice_fits():
    with two_slices() as c:
        # 24-pod gang > 16 chips/slice: impossible single-slice,
        # possible as 16 + 8 over DCN
        group = PodGroup("dp", min_member=24, allow_dcn=True)
        nodes = []
        for i in range(24):
            n, a = c.schedule(c.make_pod(f"d-{i}", tpu=1, group=group))
            nodes.append((n, a))
        res = c.extender.gang.reservation("default", "dp")
        assert res.committed and res.spans_dcn
        assert set(res.slice_coords) == {"slice-a", "slice-b"}
        assert res.total_chips() == 24
        # every member's chips live in exactly one slice
        for key, (sid, coords) in res.assigned.items():
            assert sid in ("slice-a", "slice-b")
            assert len(coords) == 1
        # gang slice-context env rides the alloc annotation
        _, alloc = nodes[0]
        assert alloc.env["TPU_KUBE_GANG_NUM_SLICES"] == "2"
        assert alloc.env["TPU_KUBE_GANG_SLICES"] == "slice-a,slice-b"
        assert alloc.env["TPU_KUBE_GANG_SLICE_INDEX"] in ("0", "1")


def test_without_allow_dcn_oversized_gang_fails():
    with two_slices() as c:
        group = PodGroup("strict", min_member=24)
        with pytest.raises(RuntimeError, match="no contiguous"):
            c.schedule(c.make_pod("s-0", tpu=1, group=group))


def test_dcn_gang_prefers_single_slice_when_it_fits():
    with two_slices() as c:
        group = PodGroup("dp", min_member=8, allow_dcn=True)
        for i in range(8):
            c.schedule(c.make_pod(f"d-{i}", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "dp")
        assert res.committed and not res.spans_dcn


def test_dcn_sub_boxes_are_contiguous_per_slice():
    with two_slices() as c:
        group = PodGroup("dp", min_member=20, allow_dcn=True)
        c.schedule(c.make_pod("d-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "dp")
        assert res.spans_dcn
        for sid, coords in res.slice_coords.items():
            # each sub-hold is a union of axis-aligned boxes; at minimum it
            # must be connected within the slice mesh
            mesh = c.slices[sid]
            region = set(coords)
            seen = {next(iter(sorted(region)))}
            frontier = list(seen)
            while frontier:
                cur = frontier.pop()
                for nb in mesh.neighbors(cur):
                    if nb in region and nb not in seen:
                        seen.add(nb)
                        frontier.append(nb)
            assert seen == region, f"{sid} sub-hold is disconnected"


def test_dcn_gang_fault_in_one_subslice_rolls_back_whole_gang():
    with two_slices() as c:
        group = PodGroup("fragile", min_member=24, allow_dcn=True)
        c.schedule(c.make_pod("f-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "fragile")
        assert res.spans_dcn
        # fault an UNASSIGNED chip in one sub-slice
        sid = sorted(res.slice_coords)[0]
        victim = sorted(res.unassigned_in(sid))[0]
        hosts = c.extender.state.hosts_by_coord(sid)
        node = hosts[victim]
        index = next(
            ch.index for ch in c.nodes[node].chips if ch.coord == victim
        )
        c.inject_fault(node, index)
        c.schedule(c.make_pod("f-1", tpu=1, group=group))
        assert c.extender.gang.rollbacks == 1
        res2 = c.extender.gang.reservation("default", "fragile")
        assert victim not in res2.slice_coords.get(sid, set())
        assert c.extender.state.allocation("default/f-0") is None


def test_dcn_gang_restart_restore_committed():
    from tpukube.sched.extender import Extender

    with two_slices() as c:
        group = PodGroup("dp", min_member=24, allow_dcn=True)
        for i in range(24):
            c.schedule(c.make_pod(f"d-{i}", tpu=1, group=group))
        ext = Extender(c.config)
        for obj in c.node_objects():
            ext.state.upsert_node(
                obj["metadata"]["name"], obj["metadata"]["annotations"]
            )
        ext.rebuild_from_pods(
            [p["metadata"]["annotations"] for p in c.pods.values()]
        )
        res = ext.gang.reservation("default", "dp")
        assert res is not None and res.committed and res.spans_dcn
        assert res.total_chips() == 24


def test_dcn_gang_blocks_non_gang_poaching_in_both_slices():
    with two_slices() as c:
        # 28 = 16 (full slice) + 12 (3x4 box) — both single boxes
        group = PodGroup("dp", min_member=28, allow_dcn=True)
        c.schedule(c.make_pod("d-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "dp")
        assert res.total_chips() == 28
        # only 4 chips remain cluster-wide for non-gang pods
        for i in range(4):
            c.schedule(c.make_pod(f"solo-{i}", tpu=1))
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule(c.make_pod("solo-4", tpu=1))


def test_dcn_split_takes_at_most_one_box_per_slice():
    """A fragmented slice must contribute at most ONE contiguous box —
    disjoint unions would break the one-sub-mesh-per-slice contract the
    TPU_KUBE_GANG_* env promises the in-pod runtime."""
    with two_slices() as c:
        # fill BOTH slices completely, remembering who owns which chip
        owners = {}  # (slice, coord) -> pod name
        for i in range(32):
            name = f"fill-{i}"
            node, a = c.schedule(c.make_pod(name, tpu=1))
            sid = c.extender.state.slice_of_node(node)
            for co in a.coords:
                owners[(sid, co)] = name
        # free exactly the two OUTER columns (x=0 and x=3) of slice-a:
        # 8 free chips in two disjoint 4-chip regions; slice-b stays full
        for (sid, co), name in owners.items():
            if sid == "slice-a" and co[0] in (0, 3):
                c.delete_pod(name)
        occ = c.extender.state.occupied_coords("slice-a")
        assert {c_[0] for c_ in occ} == {1, 2}
        # an 8-member DCN gang cannot be served by 4+4 disjoint boxes in
        # one slice: the split takes one box per slice, so it must refuse
        group = PodGroup("dp", min_member=8, allow_dcn=True)
        with pytest.raises(RuntimeError, match="not coverable|no contiguous"):
            c.schedule(c.make_pod("d-0", tpu=1, group=group))
        # a 4-member DCN gang fits in one column's single box
        small = PodGroup("small", min_member=4, allow_dcn=True)
        for i in range(4):
            c.schedule(c.make_pod(f"s-{i}", tpu=1, group=small))
        assert c.extender.gang.reservation("default", "small").committed


def test_dcn_split_preemption_evicts_to_cover():
    """A full-cluster allow_dcn gang preempts across slices: one cheap
    victim blocks the 16+16 split; it must be evicted, not wedge the gang."""
    with two_slices() as c:
        burst = []
        n0, _ = c.schedule(c.make_pod("burst-0", tpu=1, priority=1))
        group = PodGroup("mega", min_member=32, allow_dcn=True)
        for i in range(32):
            c.schedule(c.make_pod(f"m-{i}", tpu=1, group=group, priority=100))
        res = c.extender.gang.reservation("default", "mega")
        assert res.committed and res.spans_dcn
        assert res.total_chips() == 32
        assert c.extender.preemptions == 1
        assert c.extender.state.allocation("default/burst-0") is None


def test_mesh_from_alloc_env_builds_dcn_mesh():
    import jax

    from tpukube.workload.meshenv import mesh_from_alloc_env

    env = {
        "TPU_VISIBLE_DEVICES": "0",
        "TPU_KUBE_DEVICE_IDS": "tpu-0",
        "TPU_KUBE_CHIP_COORDS": "0,0,0",
        "TPU_KUBE_MESH_DIMS": "4,4,1",
        "TPU_KUBE_GANG_NUM_SLICES": "2",
        "TPU_KUBE_GANG_SLICES": "slice-a,slice-b",
        "TPU_KUBE_GANG_SLICE_INDEX": "0",
    }
    mesh, pe = mesh_from_alloc_env(env, devices=jax.devices()[:8], tp=2)
    assert pe.spans_dcn
    assert mesh.axis_names == ("dcn", "dp", "tp")
    assert mesh.devices.shape == (2, 2, 2)
    with pytest.raises(ValueError, match="divide"):
        mesh_from_alloc_env(env, devices=jax.devices()[:7])


def test_shaped_allow_dcn_pod_group_rejected_at_construction():
    with pytest.raises(ValueError, match="incompatible"):
        PodGroup("bad", min_member=4, shape=(2, 2, 1), allow_dcn=True)


def test_dcn_gang_env_projected_as_per_key_annotations():
    """The user-facing DCN contract end to end: a 2-slice gang bound
    through the real bind effector (pod_binder) leaves each member pod
    carrying the per-key gang annotations deploy/gang-job-example.yaml
    projects into TPU_KUBE_GANG_* container env — both slice indices
    represented, every annotation agreeing with the alloc blob's env."""
    from tpukube import apiserver as apisrv

    with two_slices() as c:
        api = apisrv.FakeApiServer()
        c.extender.binder = apisrv.pod_binder(api)
        group = PodGroup("dcn-train", min_member=20, allow_dcn=True)
        for i in range(20):
            pod = c.make_pod(f"t-{i}", tpu=1, priority=10, group=group)
            api.upsert_pod(pod)
            c.schedule(pod)
        seen_idx = set()
        for i in range(20):
            annos = api.get_pod("default", f"t-{i}")["metadata"]["annotations"]
            alloc_env = codec.decode_alloc(annos[codec.ANNO_ALLOC]).env
            for var, anno in codec.GANG_ENV_TO_ANNO.items():
                assert annos[anno] == alloc_env[var], (var, annos)
            assert annos["tpu.qiniu.com/gang-num-slices"] == "2"
            assert annos["tpu.qiniu.com/gang-slices"] == "slice-a,slice-b"
            seen_idx.add(annos["tpu.qiniu.com/gang-slice-index"])
        assert seen_idx == {"0", "1"}

"""Extender webhook tests over real HTTP (SimCluster plays kube-scheduler)."""

import json
import urllib.error
import urllib.request

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import TopologyCoord
from tpukube.sim import SimCluster


@pytest.fixture
def cluster():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:  # 4 nodes x 4 chips
        yield c


def test_filter_prioritize_bind_cycle(cluster):
    pod = cluster.make_pod("train-0", tpu=2)
    node, alloc = cluster.schedule(pod)
    assert node in cluster.nodes
    assert len(alloc.device_ids) == 2
    assert alloc.node_name == node
    assert pod["spec"]["nodeName"] == node
    assert codec.ANNO_ALLOC in pod["metadata"]["annotations"]
    assert cluster.utilization() == pytest.approx(2 / 16)


def test_unschedulable_when_too_big(cluster):
    pod = cluster.make_pod("huge", tpu=5)  # nodes have 4 chips
    with pytest.raises(RuntimeError, match="unschedulable"):
        cluster.schedule(pod)


def test_capacity_exhaustion_and_release(cluster):
    for i in range(4):
        cluster.schedule(cluster.make_pod(f"p{i}", tpu=4))
    assert cluster.utilization() == 1.0
    with pytest.raises(RuntimeError, match="unschedulable"):
        cluster.schedule(cluster.make_pod("p4", tpu=1))
    cluster.delete_pod("p0")
    node, alloc = cluster.schedule(cluster.make_pod("p5", tpu=4))
    assert len(alloc.device_ids) == 4


def test_unhealthy_chip_excluded(cluster):
    cluster.inject_fault("host-0-0-0", 0)
    # every node can still take 3 chips; host-0-0-0 can't take 4
    pod = cluster.make_pod("four", tpu=4)
    node, _ = cluster.schedule(pod)
    assert node != "host-0-0-0"
    # fill remaining nodes; a 4-chip pod is now unschedulable
    cluster.schedule(cluster.make_pod("four-b", tpu=4))
    cluster.schedule(cluster.make_pod("four-c", tpu=4))
    with pytest.raises(RuntimeError, match="unschedulable"):
        cluster.schedule(cluster.make_pod("four-d", tpu=4))
    # but a 3-chip pod fits on the degraded node
    node, alloc = cluster.schedule(cluster.make_pod("three", tpu=3))
    assert node == "host-0-0-0"
    assert "tpu-0" not in alloc.device_ids


def test_non_tpu_pod_passes_filter(cluster):
    pod = cluster.make_pod("web", tpu=0)
    args = {"Pod": pod, "Nodes": {"Items": cluster.node_objects()}}
    res = cluster._post("/filter", args)
    assert len(res["Nodes"]["Items"]) == 4
    assert res["FailedNodes"] == {}


def test_binpack_vs_spread_scoring():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SCORE_MODE": "binpack",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("seed", tpu=1))
        # binpack: next pod lands on the same (fullest) node
        n1, _ = c.schedule(c.make_pod("next", tpu=1))
        seed_node = c.extender.state.allocation("default/seed").node_name
        assert n1 == seed_node
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SCORE_MODE": "spread",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("seed", tpu=1))
        n1, _ = c.schedule(c.make_pod("next", tpu=1))
        seed_node = c.extender.state.allocation("default/seed").node_name
        assert n1 != seed_node


def test_vtpu_node_pool():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"}, vtpu_shares=2) as c:
        # vTPU pod only fits the vTPU node
        node, alloc = c.schedule(c.make_pod("infer-0", vtpu=1))
        assert node == "host-0-0-0"
        assert "frac" in alloc.device_ids[0]
        # second share rides the SAME chip (binpack within node)
        node2, alloc2 = c.schedule(c.make_pod("infer-1", vtpu=1))
        assert node2 == "host-0-0-0"
        chip = alloc.device_ids[0].split("-frac")[0]
        assert alloc2.device_ids[0].startswith(chip)
        assert alloc2.device_ids[0] != alloc.device_ids[0]
        # whole-chip pod avoids the vTPU node
        node3, _ = c.schedule(c.make_pod("train", tpu=4))
        assert node3 != "host-0-0-0"


def test_vtpu_release_never_reissues_live_share_id():
    # regression: minting by used-share COUNT re-issued a released share's
    # id while its sibling was still live (double-booked HBM quota)
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"}, vtpu_shares=2) as c:
        _, a = c.schedule(c.make_pod("a", vtpu=1))
        _, b = c.schedule(c.make_pod("b", vtpu=1))
        assert a.device_ids != b.device_ids
        c.delete_pod("a")
        _, c2 = c.schedule(c.make_pod("c", vtpu=1))
        assert c2.device_ids != b.device_ids  # b's share is still live
        # with both live again, the chip (2 shares) is exactly full
        live = {d for x in (b, c2) for d in x.device_ids}
        assert len(live) == 2


def test_bind_succeeds_on_disconnected_free_chips(cluster):
    # regression: filter counts free chips, bind planned only connected
    # regions — diagonal survivors on a host must still be allocatable
    for i in range(4):
        cluster.schedule(cluster.make_pod(f"s{i}", tpu=1))
    # all four singles land on one or two hosts; find a host with >= 2 pods
    # and release a diagonal pair to leave disconnected free chips
    from collections import defaultdict
    by_node = defaultdict(list)
    for key, pod in list(cluster.pods.items()):
        alloc = cluster.extender.state.allocation(key)
        if alloc:
            by_node[alloc.node_name].append((key, alloc))
    node, pods = max(by_node.items(), key=lambda kv: len(kv[1]))
    if len(pods) >= 3:
        # release two pods whose chips are diagonal (not mesh neighbors)
        mesh = cluster.mesh
        for i in range(len(pods)):
            for j in range(i + 1, len(pods)):
                ci, cj = pods[i][1].coords[0], pods[j][1].coords[0]
                if cj not in mesh.neighbors(ci):
                    cluster.delete_pod(pods[i][0].split("/")[1])
                    cluster.delete_pod(pods[j][0].split("/")[1])
                    node2, alloc = cluster.schedule(
                        cluster.make_pod("diag", tpu=2)
                    )
                    assert len(alloc.device_ids) == 2
                    return
    # topology packed too tightly to build the scenario — still fine
    assert True


def test_restart_rebuild_from_pod_annotations(cluster):
    cluster.schedule(cluster.make_pod("a", tpu=2))
    cluster.schedule(cluster.make_pod("b", tpu=3))
    util_before = cluster.utilization()

    # new extender process: rebuild ledger from pod annotations
    from tpukube.sched.extender import Extender
    fresh = Extender(cluster.config)
    for obj in cluster.node_objects():
        fresh.state.upsert_node(
            obj["metadata"]["name"], obj["metadata"]["annotations"]
        )
    restored = fresh.state.rebuild_from_pods(
        [p["metadata"]["annotations"] for p in cluster.pods.values()]
    )
    assert len(restored) == 2
    assert fresh.state.utilization() == pytest.approx(util_before)


def test_bad_json_is_400(cluster):
    req = urllib.request.Request(
        f"{cluster.base_url}/filter", data=b"not json",
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=5)
    assert e.value.code == 400


def test_bind_without_filter_is_clean_error(cluster):
    res = cluster._post("/bind", {
        "PodName": "ghost", "PodNamespace": "default",
        "PodUID": "u", "Node": "host-0-0-0",
    })
    assert "without a preceding filter" in res["Error"]


def test_healthz(cluster):
    with urllib.request.urlopen(f"{cluster.base_url}/healthz", timeout=5) as r:
        body = json.loads(r.read())
    assert body["ok"] is True


def test_restart_rebuild_preserves_gang_granularity(cluster):
    """After a restart, running gang members must NOT become individually
    evictable free-standing pods: preemption stays all-or-nothing."""
    from tpukube.core.types import (
        RESOURCE_TPU, ContainerInfo, PodGroup, PodInfo, ResourceList,
    )
    from tpukube.sched.extender import Extender

    low = PodGroup("low", min_member=8)
    for i in range(8):
        cluster.schedule(cluster.make_pod(f"lo-{i}", tpu=1, priority=10,
                                          group=low))
    for i in range(8):
        cluster.schedule(cluster.make_pod(f"solo-{i}", tpu=1, priority=15))

    # restart: new extender rebuilt purely from pod annotations
    fresh = Extender(cluster.config)
    for obj in cluster.node_objects():
        fresh.state.upsert_node(
            obj["metadata"]["name"], obj["metadata"]["annotations"]
        )
    restored = fresh.rebuild_from_pods(
        [p["metadata"]["annotations"] for p in cluster.pods.values()]
    )
    assert restored == 16
    res = fresh.gang.reservation("default", "low")
    assert res is not None and res.committed
    assert len(res.coords) == 8

    # a prio-100 4-chip gang arrives; 4 gang members (cost 40) would be the
    # cheapest individual victims, but the gang must be priced whole (80),
    # so the 4 solos (cost 60) die instead
    vip_pod = PodInfo(
        name="vip-0", namespace="default", priority=100,
        group=PodGroup("vip", min_member=4),
        containers=[ContainerInfo("main", ResourceList({RESOURCE_TPU: 1}))],
    )
    feasible, _ = fresh.filter(vip_pod, cluster.node_objects())
    assert feasible, "vip gang found no feasible nodes after preemption"
    # two-phase preemption: planning at filter evicts NOBODY
    assert all(
        fresh.state.allocation(f"default/solo-{i}") is not None
        for i in range(8)
    ), "filter must only plan; victims keep chips until first bind"
    # the first member's bind executes the plan (and then waits for the
    # victims' termination before any member may start)
    from tpukube.sched.extender import ExtenderError

    with pytest.raises(ExtenderError, match="finish terminating"):
        fresh.bind("vip-0", "default", "", feasible[0]["metadata"]["name"])
    low_alive = [
        i for i in range(8)
        if fresh.state.allocation(f"default/lo-{i}") is not None
    ]
    assert low_alive == list(range(8)), (
        f"restart broke gang all-or-nothing: survivors {low_alive}"
    )
    evicted_solos = [
        i for i in range(8)
        if fresh.state.allocation(f"default/solo-{i}") is None
    ]
    assert len(evicted_solos) == 4
    # victims confirmed gone (the executor's job): the bind proceeds
    for pk in list(fresh.pending_evictions):
        fresh.handle("victim_gone", {"pod_key": pk})
    fresh.bind("vip-0", "default", "", feasible[0]["metadata"]["name"])
    assert fresh.state.allocation("default/vip-0") is not None


def _vip_gang_pod(name: str, min_member: int = 4):
    from tpukube.core.types import (
        RESOURCE_TPU, ContainerInfo, PodGroup, PodInfo, ResourceList,
    )

    return PodInfo(
        name=name, namespace="default", priority=100,
        group=PodGroup("vip", min_member=min_member),
        containers=[ContainerInfo("main", ResourceList({RESOURCE_TPU: 1}))],
    )


def test_unbound_preempting_gang_never_evicts(cluster):
    """Two-phase preemption, phase one only: a gang that filters (plans
    victims) but NEVER binds must cost no pod its chips — the TTL sweep
    drops the reservation and the victims keep running."""
    import time as _time

    for i in range(16):
        cluster.schedule(cluster.make_pod(f"s-{i}", tpu=1, priority=5))
    ext = cluster.extender
    feasible, _ = ext.filter(_vip_gang_pod("vip-0"), cluster.node_objects())
    assert feasible, "preemption plan should open feasible nodes"
    res = ext.gang.reservation("default", "vip")
    assert res is not None and res.pending_victims
    assert ext.preemptions == 0
    assert not ext.pending_evictions
    assert all(
        ext.state.allocation(f"default/s-{i}") is not None for i in range(16)
    ), "filter must only plan, not evict"

    ttl = cluster.config.reservation_ttl_seconds
    rolled = ext.gang.sweep(now=_time.monotonic() + ttl + 1)
    assert ("default", "vip") in rolled
    assert ext.gang.reservation("default", "vip") is None
    assert all(
        ext.state.allocation(f"default/s-{i}") is not None for i in range(16)
    ), "TTL rollback of an unbound preemptor must leave victims running"
    assert not ext.pending_evictions
    assert ext.preemptions == 0


def test_preemption_executes_once_at_first_bind(cluster):
    """Phase two: the FIRST member bind executes the eviction plan (then
    waits for victim termination); later member binds must not evict
    again. Until every victim is confirmed gone, NO member bind proceeds
    — on a single-owner TPU runtime a gang pod started while its victim's
    containers still hold the chips crash-loops through the whole grace
    period."""
    from tpukube.sched.extender import ExtenderError

    for i in range(16):
        cluster.schedule(cluster.make_pod(f"s-{i}", tpu=1, priority=5))
    ext = cluster.extender
    feasible, _ = ext.filter(_vip_gang_pod("vip-0"), cluster.node_objects())
    target = feasible[0]["metadata"]["name"]
    with pytest.raises(ExtenderError, match="finish terminating"):
        ext.bind("vip-0", "default", "", target)
    assert ext.preemptions == 4
    evicted = [
        i for i in range(16)
        if ext.state.allocation(f"default/s-{i}") is None
    ]
    assert len(evicted) == 4
    assert len(ext.pending_evictions) == 4
    res = ext.gang.reservation("default", "vip")
    assert len(ext.gang.terminating_victims_of(res)) == 4
    # victims' chips stay masked from every placement while terminating
    assert ext.gang.terminating_count() == 4

    # a sibling member is gated exactly the same way
    feasible2, _ = ext.filter(_vip_gang_pod("vip-1"), cluster.node_objects())
    assert feasible2
    with pytest.raises(ExtenderError, match="victim"):
        ext.bind("vip-1", "default", "", feasible2[0]["metadata"]["name"])
    assert ext.preemptions == 4, "second bind must not re-execute the plan"
    assert len(ext.pending_evictions) == 4

    # the executor confirms the victims gone: binds proceed, once each
    for pk in list(ext.pending_evictions):
        ext.handle("victim_gone", {"pod_key": pk})
    assert ext.gang.terminating_count() == 0
    ext.bind("vip-0", "default", "", target)
    feasible3, _ = ext.filter(_vip_gang_pod("vip-1"), cluster.node_objects())
    ext.bind("vip-1", "default", "", feasible3[0]["metadata"]["name"])
    assert ext.preemptions == 4
    assert ext.state.allocation("default/vip-0") is not None
    assert ext.state.allocation("default/vip-1") is not None


def test_failing_first_bind_leaves_victims_untouched(cluster):
    """Phase two is guarded: a first bind that cannot commit (a planned
    chip went unhealthy between filter and bind) must NOT execute the
    eviction plan — victims keep their chips, the plan stays pending."""
    from tpukube.sched.extender import ExtenderError

    for i in range(16):
        cluster.schedule(cluster.make_pod(f"s-{i}", tpu=1, priority=5))
    ext = cluster.extender
    feasible, _ = ext.filter(_vip_gang_pod("vip-0"), cluster.node_objects())
    res = ext.gang.reservation("default", "vip")
    assert res is not None and res.pending_victims
    target = feasible[0]["metadata"]["name"]

    # a reserved chip on the bind target dies AFTER the filter; refresh
    # the extender's node views without a gang sweep (upsert, not filter)
    view = ext.state.node(target)
    sick = next(c for c in view.info.chips if c.coord in res.coords)
    cluster.inject_fault(target, sick.index)
    for obj in cluster.node_objects():
        ext.state.upsert_node(
            obj["metadata"]["name"], obj["metadata"]["annotations"]
        )

    with pytest.raises(ExtenderError, match="unhealthy"):
        ext.bind("vip-0", "default", "", target)
    # no eviction happened and the plan is still pending
    assert ext.preemptions == 0
    assert not ext.pending_evictions
    assert all(
        ext.state.allocation(f"default/s-{i}") is not None for i in range(16)
    ), "a failed first bind must not cost victims their chips"
    assert res.pending_victims


def test_restart_rebuild_mid_assembly_gang(cluster):
    """Restart while a gang is half-assembled: either the reservation is
    re-completed to a full contiguous slice (members keep their chips and
    late members can still join) or the half-gang is rolled back whole —
    never left as a broken committed=False shell that strands members."""
    from tpukube.core.types import PodGroup
    from tpukube.sched.extender import Extender

    # assemble only 4 of an 8-member gang (schedule members one at a time,
    # stopping early — the reservation exists, uncommitted)
    group = PodGroup("half", min_member=8)
    for i in range(4):
        cluster.schedule(cluster.make_pod(f"h-{i}", tpu=1, priority=10,
                                          group=group))
    res = cluster.extender.gang.reservation("default", "half")
    assert res is not None and not res.committed

    fresh = Extender(cluster.config)
    for obj in cluster.node_objects():
        fresh.state.upsert_node(
            obj["metadata"]["name"], obj["metadata"]["annotations"]
        )
    fresh.rebuild_from_pods(
        [p["metadata"]["annotations"] for p in cluster.pods.values()]
    )
    res2 = fresh.gang.reservation("default", "half")
    if res2 is not None:
        # re-completed: full-size slice containing every member's chips
        assert len(res2.coords) == 8
        assert res2.assigned.keys() == {f"default/h-{i}" for i in range(4)}
        assert len(res2.unassigned_coords()) == 4
    else:
        # rolled back whole: every member released and queued for eviction
        assert all(
            fresh.state.allocation(f"default/h-{i}") is None for i in range(4)
        )
        assert set(fresh.pending_evictions) == {
            f"default/h-{i}" for i in range(4)
        }


def test_restart_rebuild_mid_assembly_gang_uncompletable():
    """If the surviving members' chips cannot be extended to a full
    contiguous slice, the restored half-gang must be rolled back whole.

    Built from hand-made annotations: a live cluster can't produce this
    state (the reservation masks its unassigned chips, which then remain
    free and completable after restart) — but annotations on a real
    apiserver outlive the reservation, so a restart CAN find members whose
    slice was since stolen (e.g. the old extender rolled the gang back by
    TTL and new pods took the chips, then it crashed before evictions ran).
    """
    from tpukube.core.config import load_config
    from tpukube.core.types import AllocResult, PodGroup, TopologyCoord
    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:  # only used to mint node annotations
        group = PodGroup("doomed", min_member=8)
        pods = []
        # 4 gang members hold host-0-0-0's 2x2 block (chips 0..3)
        host0 = c.mesh.coords_of_host("host-0-0-0")
        for i in range(4):
            anno = dict(codec.pod_group_annotations(group))
            anno[codec.ANNO_ALLOC] = codec.encode_alloc(AllocResult(
                pod_key=f"default/d-{i}", node_name="host-0-0-0",
                device_ids=[f"tpu-{i}"], coords=[host0[i]], priority=10,
            ))
            pods.append(anno)
        # every other chip is held by solo pods: no free chip anywhere else
        for host in c.mesh.all_hosts():
            if host == "host-0-0-0":
                continue
            for i, coord in enumerate(c.mesh.coords_of_host(host)):
                pods.append({codec.ANNO_ALLOC: codec.encode_alloc(AllocResult(
                    pod_key=f"default/solo-{host}-{i}", node_name=host,
                    device_ids=[f"tpu-{i}"], coords=[coord], priority=0,
                ))})
        fresh = Extender(c.config)
        for obj in c.node_objects():
            fresh.state.upsert_node(
                obj["metadata"]["name"], obj["metadata"]["annotations"]
            )
        fresh.rebuild_from_pods(pods)
        # no 8-chip box can contain the 2x2 corner (only 4 chips are free
        # in total): the half-gang must be rolled back whole
        assert fresh.gang.reservation("default", "doomed") is None
        assert all(
            fresh.state.allocation(f"default/d-{i}") is None for i in range(4)
        )
        assert sorted(fresh.pending_evictions) == [
            f"default/d-{i}" for i in range(4)
        ]
        assert fresh.gang.rollbacks == 1
        # the 12 solos survive untouched
        assert fresh.state.utilization() == pytest.approx(12 / 16)


def test_sharing_mode_switch_rejected_under_live_allocations():
    """A node flipping shares_per_chip while pods hold its chips would
    double-book (old ids carry old-mode weights) — the ledger refuses."""
    import pytest

    from tpukube.core import codec
    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import AllocResult, ChipInfo, NodeInfo, TopologyCoord
    from tpukube.sched.state import ClusterState, StateError

    mesh = MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))
    def node(shares):
        return NodeInfo(
            name="host-0-0-0",
            chips=[ChipInfo(f"c{i}", i, co, hbm_bytes=16 << 30)
                   for i, co in enumerate(mesh.coords_of_host("host-0-0-0"))],
            shares_per_chip=shares,
        )

    st = ClusterState()
    st.upsert_node("host-0-0-0", codec.annotate_node(node(1), mesh))
    st.commit(AllocResult(pod_key="d/p", node_name="host-0-0-0",
                          device_ids=["tpu-0"],
                          coords=[TopologyCoord(0, 0, 0)]))
    with pytest.raises(StateError, match="drain"):
        st.upsert_node("host-0-0-0", codec.annotate_node(node(4), mesh))
    # after the pod is gone the switch is fine
    st.release("d/p")
    st.upsert_node("host-0-0-0", codec.annotate_node(node(4), mesh))
    assert st.node("host-0-0-0").shares_per_chip == 4


def test_node_cache_capable_names_mode(cluster):
    """The nodeCacheCapable leg of the extender protocol: after the node
    cache is primed, NodeNames-only requests are answered purely from the
    cache with a names-only result — the hot-path shape that keeps webhook
    payloads off the wire."""
    pod = cluster.make_pod("p0", tpu=1)
    primed = cluster._post(
        "/filter", {"Pod": pod, "Nodes": {"Items": cluster.node_objects()}}
    )
    assert primed["Nodes"]["Items"]

    names = [o["metadata"]["name"] for o in cluster.node_objects()]
    pod2 = cluster.make_pod("p1", tpu=1)
    res = cluster._post("/filter", {"Pod": pod2, "NodeNames": names})
    assert "Nodes" not in res  # names-only response in names mode
    assert sorted(res["NodeNames"]) == sorted(names)
    assert res["FailedNodes"] == {}

    pres = cluster._post(
        "/prioritize", {"Pod": pod2, "NodeNames": res["NodeNames"]}
    )
    assert {e["Host"] for e in pres} == set(names)
    assert all(e["Score"] >= 0 for e in pres)

    # a name the cache has never seen is infeasible with a reason
    res2 = cluster._post(
        "/filter",
        {"Pod": cluster.make_pod("p2", tpu=1), "NodeNames": ["ghost"]},
    )
    assert res2["NodeNames"] == []
    assert "ghost" in res2["FailedNodes"]

    # neither nodes nor names is a schema error (HTTP 400), not a crash
    try:
        cluster._post("/filter", {"Pod": pod})
        raise AssertionError("expected HTTP 400")
    except RuntimeError as e:
        assert "400" in str(e)

"""Decision provenance (ISSUE 12 tentpole): the DecisionLog ring,
explain assembly across webhook and batch paths, cycle phase
profiling, per-tenant burn windows, the decision-provenance lint, and
off-is-off parity."""

import json
import urllib.error
import urllib.request

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.obs.decisions import DecisionLog, explain_doc, format_explain
from tpukube.sim import SimCluster

TENANT_LABEL = "tpu.qiniu.com/tenant"


def _cfg(extra=None):
    env = {
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
    }
    env.update(extra or {})
    return load_config(env=env)


# -- ring + sampling ---------------------------------------------------------

def test_ring_bounds():
    log = DecisionLog(capacity=8, sample_rate=1.0)
    for i in range(50):
        log.record(f"default/p{i}", "filter", feasible=1)
    assert len(log.events()) == 8
    assert log.recorded == 50
    # oldest rotated out, newest retained
    pods = [e["pod"] for e in log.events()]
    assert pods == [f"default/p{i}" for i in range(42, 50)]
    assert log.record_seconds > 0


def test_sampling_determinism_seeded():
    keys = [f"default/pod-{i}" for i in range(400)]
    a = DecisionLog(sample_rate=0.5, seed=7)
    b = DecisionLog(sample_rate=0.5, seed=7)
    c = DecisionLog(sample_rate=0.5, seed=8)
    picks_a = {k for k in keys if a.wants(k)}
    picks_b = {k for k in keys if b.wants(k)}
    picks_c = {k for k in keys if c.wants(k)}
    # deterministic per seed: two instances agree exactly
    assert picks_a == picks_b
    # a rate-0.5 hash sample lands in a sane band
    assert 100 < len(picks_a) < 300
    # a different seed selects a different set
    assert picks_a != picks_c
    # edge rates
    off = DecisionLog(sample_rate=0.0)
    on = DecisionLog(sample_rate=1.0)
    assert not any(off.wants(k) for k in keys)
    assert all(on.wants(k) for k in keys)


def test_explain_unknown_pod():
    log = DecisionLog()
    doc = log.explain("default/ghost")
    assert doc["verdict"] == "unknown"
    assert "UNKNOWN" in format_explain(doc)


def test_explain_midflight_is_pending_not_unknown():
    """Review regression: a pod with recorded stages but no
    verdict-moving one yet (filter/prioritize done, bind pending) is
    PENDING — 'no provenance recorded' above rendered why-lines would
    deny the data it just printed."""
    log = DecisionLog()
    log.record("default/mid", "filter", candidates=2, feasible=2,
               pruned={})
    log.record("default/mid", "prioritize", nodes=2,
               top=[["n0", 7], ["n1", 5]])
    doc = log.explain("default/mid")
    assert doc["verdict"] == "pending"
    assert "PENDING" in format_explain(doc)


# -- explain across the webhook path -----------------------------------------

def test_explain_placed_webhook_path():
    with SimCluster(_cfg()) as c:
        node, _ = c.schedule(c.make_pod("web", tpu=1))
        doc = c.extender.decisions.explain("default/web")
        assert doc["verdict"] == "placed"
        assert doc["node"] == node
        stages = [e["stage"] for e in doc["stages"]]
        assert "filter" in stages and "prioritize" in stages
        assert stages[-1] == "bind"
        # candidate pruning + top-k scores made it into the chain
        f = next(e for e in doc["stages"] if e["stage"] == "filter")
        assert f["feasible"] >= 1 and f["candidates"] >= f["feasible"]
        p = next(e for e in doc["stages"] if e["stage"] == "prioritize")
        assert p["top"] and p["top"][0][0] == node
        text = format_explain(doc)
        assert "PLACED" in text and node in text


def test_explain_pending_unschedulable():
    with SimCluster(_cfg()) as c:
        try:
            c.schedule(c.make_pod("huge", tpu=64))
        except RuntimeError:
            pass
        doc = c.extender.decisions.explain("default/huge")
        assert doc["verdict"] == "pending"
        f = next(e for e in doc["stages"] if e["stage"] == "filter")
        assert f["feasible"] == 0 and f["pruned"]
        # the pruning reasons name why each node refused
        assert any("wants 64 chips" in r for r in f["pruned"])


def test_explain_denied_tenancy_quota():
    cfg = _cfg({
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_QUOTAS": "a=chips:1",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("a-0", tpu=1, labels={TENANT_LABEL: "a"}))
        try:
            c.schedule(c.make_pod("a-1", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
            assert False, "quota breach must refuse"
        except RuntimeError:
            pass
        doc = c.extender.decisions.explain("default/a-1")
        assert doc["verdict"] == "denied"
        t = next(e for e in doc["stages"] if e["stage"] == "tenancy")
        assert t["verdict"] == "TenantQuotaDenied"
        assert t["tenant"] == "a"
        # shares at decision time ride the record
        assert t["dominant_share"] is not None
        # the wire refusal is chained too
        assert any(e["stage"] == "refusal" for e in doc["stages"])
        assert "quota" in format_explain(doc)


def test_explain_preempted_victim():
    with SimCluster(_cfg()) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"low-{i}", tpu=1, priority=0))
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=100,
                                  group=group))
        victims = [f"default/low-{i}" for i in range(4)]
        docs = [c.extender.decisions.explain(v) for v in victims]
        assert all(d["verdict"] == "preempted" for d in docs)
        assert any("higher-priority" in format_explain(d) for d in docs)
        # and the preemptor's chain shows the plan
        gd = c.extender.decisions.explain("default/g-0")
        assert any(e["stage"] == "preemption_plan" for e in gd["stages"])


def test_explain_released_after_completion():
    with SimCluster(_cfg()) as c:
        c.schedule(c.make_pod("done", tpu=1))
        c.complete_pod("done")
        doc = c.extender.decisions.explain("default/done")
        assert doc["verdict"] == "released"


# -- explain across the batch path + phase profiling -------------------------

def test_explain_batch_path_and_phases():
    cfg = _cfg({"TPUKUBE_BATCH_ENABLED": "1"})
    with SimCluster(cfg, in_process=True) as c:
        pods = [c.make_pod(f"b-{i}", tpu=1) for i in range(3)]
        placed = c.schedule_pending(pods)
        assert len(placed) == 3
        ext = c.extender
        doc = ext.decisions.explain("default/b-0")
        assert doc["verdict"] == "placed"
        plan = next(e for e in doc["stages"]
                    if e["stage"] == "cycle_plan")
        assert plan["arm"] == "fast"
        assert plan["assumed"] is True
        assert plan["snapshot"] in ("delta", "rebuild", "cached")
        assert plan["queue_age_s"] is not None
        assert any(e["stage"] == "admit" for e in doc["stages"])
        b = next(e for e in doc["stages"] if e["stage"] == "bind")
        assert b["served_from"] == "plan"
        # phase histogram observed pin/plan (and commit via /bind)
        text = ext.phase_hist.render()
        assert 'phase="pin"' in text and 'phase="plan"' in text
        assert 'phase="commit"' in text
        # cycle spans landed in the decision trace for the timeline
        kinds = {e["request"].get("name") for e in ext.trace.events()
                 if e["kind"] == "span"}
        assert {"cycle_pin", "cycle_plan"} <= kinds


def test_gang_batch_arm_recorded():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
        "TPUKUBE_BATCH_ENABLED": "1",
    })
    with SimCluster(cfg, in_process=True) as c:
        group = PodGroup("gg", min_member=4)
        pods = [c.make_pod(f"gg-{i}", tpu=1, priority=10, group=group)
                for i in range(4)]
        placed = c.schedule_pending(pods)
        assert len(placed) == 4
        doc = c.extender.decisions.explain("default/gg-0")
        plan = next(e for e in doc["stages"]
                    if e["stage"] == "cycle_plan")
        assert plan["arm"] == "gang_batch"
        assert any(e["stage"] == "gang_reserve"
                   for e in doc["stages"])


def test_cycle_queue_age_percentiles_in_stats():
    cfg = _cfg({"TPUKUBE_BATCH_ENABLED": "1",
                "TPUKUBE_BATCH_MAX_PODS": "1"})
    from tpukube.core.clock import FakeClock
    from tpukube.sched import kube

    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        # admit two pods; the 1-pod batch cap leaves one queued
        for i in range(2):
            ext.admit(kube.pod_from_k8s(c.make_pod(f"q-{i}", tpu=1)))
        clock.advance(5.0)
        stats = ext.cycle.stats()
        assert stats["queue_depth"] == 2
        assert stats["queue_oldest_age_s"] >= 5.0
        assert stats["queue_age_p50_s"] >= 5.0
        assert stats["queue_age_p99_s"] >= stats["queue_age_p50_s"]
        # planning drains the queue but the pods are still PENDING
        # (assumed, no bind yet): the admit stamps — and the ages —
        # survive until an actual bind or release retires them
        ext.plan_pending()
        stats = ext.cycle.stats()
        assert stats["queue_depth"] == 0
        assert stats["queue_oldest_age_s"] >= 5.0
        for i in range(2):
            ext.handle("release", {"pod_key": f"default/q-{i}"})
        assert ext.cycle.stats()["queue_oldest_age_s"] is None


def test_pdb_refusal_recorded():
    """Review regression: a bind refused by the PodDisruptionBudget
    precheck must land in the provenance chain — a pod stuck behind a
    PDB is exactly the incident explain must answer."""
    with SimCluster(_cfg()) as c:
        ext = c.extender
        for i in range(4):
            c.schedule(c.make_pod(f"low-{i}", tpu=1, priority=0))
        ext.evict_precheck = lambda pk: False  # PDB blocks every victim
        group = PodGroup("g", min_member=4)
        try:
            c.schedule(c.make_pod("g-0", tpu=1, priority=100,
                                  group=group), retries=2)
            assert False, "bind must be refused by the precheck"
        except RuntimeError:
            pass
        doc = ext.decisions.explain("default/g-0")
        r = next(e for e in doc["stages"] if e["stage"] == "refusal")
        assert r["kind"] == "pdb_precheck"
        assert "PodDisruptionBudget" in r["reason"]
        assert "PodDisruptionBudget" in format_explain(doc)


def test_release_clears_queued_ghost():
    """Review regression: a pod deleted while still QUEUED must leave
    the queue (and the queue-age stats) — a ghost entry would inflate
    queue_oldest_age_s forever and plan chips nobody will bind."""
    from tpukube.sched import kube

    cfg = _cfg({"TPUKUBE_BATCH_ENABLED": "1",
                "TPUKUBE_BATCH_MAX_PODS": "1"})
    with SimCluster(cfg, in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        for i in range(2):
            ext.admit(kube.pod_from_k8s(c.make_pod(f"gh-{i}", tpu=1)))
        assert ext.cycle.stats()["queue_depth"] == 2
        ext.handle("release", {"pod_key": "default/gh-0"})
        ext.handle("release", {"pod_key": "default/gh-1"})
        s = ext.cycle.stats()
        assert s["queue_depth"] == 0
        assert s["queue_oldest_age_s"] is None
        assert ext.cycle.run_pending() == 0  # nothing ghost-planned


def test_pending_age_survives_refusal_retries():
    """Review regression: a pod refused and retried for hours must
    ACCUMULATE pending-admit age — per-retry resets would hide exactly
    the starved pod the stat exists to page on. A successful bind then
    retires the stamp."""
    from tpukube.core.clock import FakeClock

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_QUOTAS": "a=chips:1",
    })
    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        ext = c.extender
        c.schedule(c.make_pod("a-0", tpu=1, labels={TENANT_LABEL: "a"}))
        pod = c.make_pod("a-1", tpu=1, labels={TENANT_LABEL: "a"})
        for _ in range(2):
            try:
                c.schedule(pod, retries=1)
            except RuntimeError:
                pass  # quota refusal; the scheduler would requeue
            clock.advance(10.0)
        try:
            c.schedule(pod, retries=1)
        except RuntimeError:
            pass
        stats = ext.cycle.stats()
        assert stats["queue_oldest_age_s"] >= 20.0
        # quota frees up: the pod binds and its stamp retires
        c.complete_pod("a-0")
        node, _ = c.schedule(pod)
        assert node
        assert ext.cycle.stats()["queue_oldest_age_s"] is None


def test_effector_failure_explains_as_pending():
    """Review regression: a bind whose apiserver effector fails is
    undone for retry — its explain must end on a failed bind stage,
    not read 'bound ... released' for a pod still Pending."""
    with SimCluster(_cfg()) as c:
        def boom(alloc):
            raise RuntimeError("apiserver down")

        c.extender.binder = boom
        try:
            c.schedule(c.make_pod("fx", tpu=1), retries=1)
            assert False, "bind must fail through the effector"
        except RuntimeError:
            pass
        doc = c.extender.decisions.explain("default/fx")
        assert doc["verdict"] == "pending"
        last = doc["stages"][-1]
        assert last["stage"] == "bind" and last["ok"] is False
        assert "apiserver bind failed" in last["error"]


def test_admit_gate_refusal_stamps_pending_age():
    """Review regression: an informer-fed pod shed at the ADMIT gate
    (never enqueued) must still accumulate pending-admit age — the
    starvation stats cover both refusal paths."""
    from tpukube.core.clock import FakeClock
    from tpukube.sched import kube

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_QUOTAS": "a=chips:1",
    })
    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        ext = c.extender
        c.schedule(c.make_pod("a-0", tpu=1, labels={TENANT_LABEL: "a"}))
        over = c.make_pod("a-over", tpu=1, labels={TENANT_LABEL: "a"})
        assert ext.admit(kube.pod_from_k8s(over)) is False  # refused
        clock.advance(30.0)
        assert ext.admit(kube.pod_from_k8s(over)) is False  # retried
        stats = ext.cycle.stats()
        assert stats["queue_depth"] == 0  # never actually enqueued
        assert stats["queue_oldest_age_s"] >= 30.0
        # deletion retires the stamp like any pending pod's
        ext.handle("release", {"pod_key": "default/a-over"})
        assert ext.cycle.stats()["queue_oldest_age_s"] is None


def test_explain_url_with_bearer_token(tmp_path, capsys):
    """Review regression: `tpukube-obs explain --url` must be usable
    against an auth-configured extender (--token-file)."""
    import socket

    import pytest

    from tpukube import cli
    from tpukube.sched.extender import (
        Extender,
        make_app,
        run_probe_server,
    )

    ext = Extender(_cfg())
    ext.decisions.record("default/p", "bind", node="n", ok=True,
                         served_from="legacy")
    app = make_app(ext, auth_token="sekrit")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stop = run_probe_server(app, "127.0.0.1", port)
    try:
        tok = tmp_path / "tok"
        tok.write_text("sekrit\n")
        rc = cli.main_obs(["explain", "default/p",
                           "--url", f"http://127.0.0.1:{port}",
                           "--token-file", str(tok)])
        assert rc == 0 and "PLACED" in capsys.readouterr().out
        with pytest.raises(urllib.error.HTTPError) as e:
            cli.main_obs(["explain", "default/p",
                          "--url", f"http://127.0.0.1:{port}"])
        assert e.value.code == 401
    finally:
        stop()


# -- off-is-off + parity -----------------------------------------------------

def test_off_is_off_exposition_and_statusz():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz
    from tpukube.sched.extender import Extender

    off = Extender(load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    text = render_extender_metrics(off)
    assert "tpukube_decisions" not in text
    assert "tpukube_cycle_phase_seconds" not in text
    assert off.decisions is None and off.phase_hist is None
    assert extender_statusz(off)["decisions"] == {"enabled": False}

    on = Extender(_cfg())
    text_on = render_extender_metrics(on)
    assert "tpukube_decisions_total" in text_on
    assert "tpukube_decisions_record_seconds_total" in text_on
    assert "tpukube_cycle_phase_seconds_bucket" in text_on
    # with provenance on, the only exposition difference is the new
    # families — every legacy series (name + labels; values carry
    # instance-local timings) renders identically
    def shape(t):
        return [ln.rsplit(" ", 1)[0] for ln in t.splitlines()]

    legacy = [ln for ln in shape(text_on)
              if "tpukube_decisions" not in ln
              and "tpukube_cycle_phase_seconds" not in ln]
    assert legacy == shape(text)
    sz = extender_statusz(on)["decisions"]
    assert sz["enabled"] is True and sz["sample_rate"] == 1.0


def test_placement_parity_decisions_on_vs_off():
    def run(enabled):
        env = {
            "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
            "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        }
        if enabled:
            env["TPUKUBE_DECISIONS_ENABLED"] = "1"
        placements = {}
        with SimCluster(load_config(env=env)) as c:
            for i in range(4):
                placements[f"p{i}"], alloc = c.schedule(
                    c.make_pod(f"p{i}", tpu=1))
                placements[f"p{i}-coords"] = [
                    list(co) for co in alloc.coords]
            group = PodGroup("g", min_member=8)
            for i in range(8):
                node, alloc = c.schedule(c.make_pod(
                    f"g{i}", tpu=1, priority=50, group=group))
                placements[f"g{i}"] = (node, [list(co)
                                              for co in alloc.coords])
        return placements

    assert run(False) == run(True)


# -- per-tenant burn windows on the fake clock -------------------------------

def test_per_tenant_burn_window_math_on_fakeclock():
    from tpukube.core.clock import FakeClock
    from tpukube.obs.registry import Histogram
    from tpukube.tenancy.core import BurnMonitor

    clock = FakeClock()
    hist = Histogram("tpukube_tenant_admission_seconds")
    mon = BurnMonitor(clock, threshold=14.4, window=60.0)
    mon.attach_tenant("tenant-admission-latency", hist,
                      threshold_le="0.25", objective=0.999)

    def observe(tenant, fast, slow):
        child = hist.labels(tenant=tenant)
        for _ in range(fast):
            child.observe(0.01)
        for _ in range(slow):
            child.observe(1.0)

    # tenant a: all fast; tenant b: half slow
    observe("a", 100, 0)
    observe("b", 50, 50)
    clock.advance(10.0)
    mon.evaluate()
    assert mon.tenant_burn("a") == 0.0
    # error ratio 0.5 over budget 0.001 = 500x
    assert abs(mon.tenant_burn("b") - 500.0) < 1.0
    assert mon.last_tenant_burn("b", "tenant-admission-latency") > 100
    assert mon.last_tenant_burn("ghost", "x") == 0.0

    # slide one window: burn is measured vs the A baseline — new
    # all-fast traffic from b dilutes but keeps history in window
    clock.advance(61.0)
    observe("b", 100, 0)
    burns1 = mon.evaluate()
    assert burns1 is not None
    tb = mon.tenant_burn("b")
    assert tb is not None and 0 < tb < 500.0

    # idle gap past two windows: per-tenant baselines reset too — no
    # stale pseudo-window judges the morning's first burst
    clock.advance(200.0)
    mon.evaluate()
    assert mon.tenant_burn("b") is None
    # traffic resumes: a fresh window pair re-measures honestly
    observe("b", 0, 10)
    clock.advance(10.0)
    mon.evaluate()
    assert mon.tenant_burn("b") is not None
    assert mon.tenant_burn("b") > 100


def test_tenant_latency_series_render_with_tenancy_on():
    from tpukube.metrics import render_extender_metrics
    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_TENANCY_ENABLED": "1",
    })
    ext = Extender(cfg)
    ext.tenants.observe_admission("teamA", 0.01)
    ext.tenants.observe_commit("teamA", 0.02)
    ext.tenants.burn.evaluate()
    text = render_extender_metrics(ext)
    assert 'tpukube_tenant_admission_seconds_bucket{le="0.25",tenant="teamA"}' in text
    assert 'tpukube_tenant_commit_seconds_bucket' in text
    assert "tpukube_tenant_slo_burn" in text
    # and tenancy-off exposition carries none of them
    off = Extender(load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    off_text = render_extender_metrics(off)
    assert "tpukube_tenant_admission_seconds" not in off_text
    assert "tpukube_tenant_slo_burn" not in off_text


def test_shed_cites_tenant_local_burn():
    """The promoted BurnMonitor: a shed's refusal message (and its
    provenance record) cite the refused tenant's own admission burn,
    not just the plane-global trigger."""
    from tpukube.sched import kube

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_BURN_WINDOW_SECONDS": "60",
    })
    with SimCluster(cfg) as c:
        ext = c.extender
        plane = ext.tenants
        # tenant a dominates the burst plane; tenant b stays under
        for i in range(6):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        c.schedule(c.make_pod("b-0", tpu=1,
                              labels={TENANT_LABEL: "b"}))
        # burn the gang SLO: slow commits past the 2.5s threshold
        for _ in range(40):
            ext.gang.commit_hist.observe(10.0)
        # give tenant a slow ADMISSIONS too, so its tenant-local burn
        # is real — then let the monitor see both
        for _ in range(20):
            plane.observe_admission("a", 1.0)
        pod = c.make_pod("a-burst", tpu=1, labels={TENANT_LABEL: "a"})
        refusal = plane.admit(kube.pod_from_k8s(pod), "qiniu.com/tpu", 1)
        assert refusal is not None and "admission shed" in refusal
        assert "tenant-local admission burn" in refusal
        doc = ext.decisions.explain("default/a-burst")
        assert doc["verdict"] == "denied"
        t = next(e for e in doc["stages"] if e["stage"] == "tenancy")
        assert t["tenant_burn"] is not None and t["tenant_burn"] > 14.4


# -- /explain route, /statusz, CLI -------------------------------------------

def test_explain_route_and_cli_file_mode(tmp_path, capsys):
    from tpukube import cli

    sink = tmp_path / "decisions.jsonl"
    cfg = _cfg({"TPUKUBE_DECISIONS_PATH": str(sink)})
    with SimCluster(cfg) as c:
        node, _ = c.schedule(c.make_pod("routed", tpu=1))
        with urllib.request.urlopen(
            f"{c.base_url}/explain?pod=default/routed", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert doc["verdict"] == "placed" and doc["node"] == node
        # bare names default the namespace
        with urllib.request.urlopen(
            f"{c.base_url}/explain?pod=routed", timeout=5
        ) as r:
            assert json.loads(r.read())["verdict"] == "placed"
        c.extender.decisions.close()  # drain the sink

    rc = cli.main_obs(["explain", "routed", "--file", str(sink)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "PLACED" in out and node in out
    rc = cli.main_obs(["explain", "default/ghost", "--file", str(sink)])
    out = capsys.readouterr().out
    assert rc == 1 and "UNKNOWN" in out
    # --json emits the raw document
    rc = cli.main_obs(["explain", "routed", "--file", str(sink),
                       "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "placed"


def test_explain_route_404_when_disabled():
    import urllib.error

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        try:
            urllib.request.urlopen(f"{c.base_url}/explain?pod=x",
                                   timeout=5)
            assert False, "must 404 with provenance off"
        except urllib.error.HTTPError as e:
            assert e.code == 404


# -- timeline: cycle spans + junk tolerance ----------------------------------

def test_timeline_cycle_spans_junk_tolerance():
    """Satellite regression: Chrome-trace export over a capture that
    mixes cluster-track cycle spans (no pod key), pod events, and torn
    junk must keep the batch structure and not crash."""
    import time as _time

    from tpukube.obs import timeline

    now = _time.time()
    events = [
        {"seq": 1, "ts": now, "kind": "span",
         "request": {"name": "cycle_pin", "pod_key": "",
                     "cycle": 1, "snapshot": "delta"}, "response": None},
        {"seq": 2, "ts": now + 0.001, "kind": "span",
         "request": {"name": "cycle_plan", "pod_key": "",
                     "cycle": 1, "pods": 3}, "response": None},
        {"seq": 3, "ts": now + 0.002, "kind": "span",
         "request": {"name": "cycle_answer", "pod_key": "default/p0",
                     "cycle": 1}, "response": None},
        # junk a torn capture can contain
        "garbage line", {"kind": "span"}, {"ts": "not-a-number"},
        {"seq": 9, "ts": now + 0.003, "kind": "bind",
         "request": {"PodName": "p0", "PodNamespace": "default"},
         "response": {}},
    ]
    doc = timeline.chrome_trace(events)
    names = {e.get("name") for e in doc["traceEvents"]}
    assert {"cycle_pin", "cycle_plan", "cycle_answer"} <= names
    # cycle_pin/plan live on the cluster track; cycle_answer on the pod
    chains = timeline.span_chains(events)
    assert chains["default/p0"] == ["cycle_answer", "bind"]
    stats = timeline.phase_stats(events)
    assert "cycle_answer" in stats


# -- decision-provenance lint ------------------------------------------------

VIOLATING_SEAM = '''\
class Gate:
    def refuse(self, pod):
        self._emit_event("DegradedMode", "extender/filter",
                         "failing safe")
        return "refused"
'''

CLEAN_SEAM = '''\
class Gate:
    def refuse(self, pod):
        self._emit_event("DegradedMode", "extender/filter",
                         "failing safe")
        if self.decisions is not None and self.decisions.wants(pod):
            self.decisions.record(pod, "refusal", kind="degraded")
        return "refused"
'''

DELEGATING_SEAM = '''\
class Gate:
    def admit(self, pod):
        self._refuse("TenantQuotaDenied", pod, "over quota")
        return "refused"

    def _refuse(self, reason, pod, message):
        dlog = self.decisions
        if dlog is not None and dlog.wants(pod):
            dlog.record(pod, "tenancy", verdict=reason)
'''


def _lint(tmp_path, rel, source):
    from tpukube.analysis.base import run_all

    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    return [f for f in run_all([tmp_path],
                               rules=["decision-provenance"])]


def test_provenance_lint_fixture_pair(tmp_path):
    bad = _lint(tmp_path, "sched/extender.py", VIOLATING_SEAM)
    assert len(bad) == 1 and bad[0].rule == "decision-provenance"
    assert "refusal seam" in bad[0].message


def test_provenance_lint_clean_fixture(tmp_path):
    assert _lint(tmp_path, "sched/extender.py", CLEAN_SEAM) == []


def test_provenance_lint_delegation_counts(tmp_path):
    """admit() delegating to the tenancy choke point is clean; the
    choke point itself is a registered seam and must record."""
    assert _lint(tmp_path, "tenancy/core.py", DELEGATING_SEAM) == []
    # strip the record from _refuse: the registered seam now fails
    broken = DELEGATING_SEAM.replace(
        "        dlog = self.decisions\n"
        "        if dlog is not None and dlog.wants(pod):\n"
        "            dlog.record(pod, \"tenancy\", verdict=reason)\n",
        "        pass\n",
    )
    bad = _lint(tmp_path, "tenancy/core.py", broken)
    assert len(bad) == 1


def test_provenance_lint_out_of_scope_ignored(tmp_path):
    assert _lint(tmp_path, "workload/other.py", VIOLATING_SEAM) == []


def test_provenance_lint_tree_clean():
    """The real tree's refusal seams all record — zero findings, zero
    waivers (the ISSUE 12 consistency satellite)."""
    import tpukube
    from tpukube.analysis.base import run_all

    findings = run_all([tpukube.__path__[0]],
                       rules=["decision-provenance"])
    assert findings == []


def test_lint_cli_lists_new_rule(capsys):
    from tpukube.analysis.cli import main

    assert main(["--list-rules"]) == 0
    assert "decision-provenance" in capsys.readouterr().out


# -- config validation -------------------------------------------------------

def test_config_validation():
    import pytest

    with pytest.raises(ValueError, match="decisions_path"):
        load_config(env={"TPUKUBE_DECISIONS_PATH": "/tmp/x.jsonl"})
    with pytest.raises(ValueError, match="decisions_sample_rate"):
        load_config(env={"TPUKUBE_DECISIONS_ENABLED": "1",
                         "TPUKUBE_DECISIONS_SAMPLE_RATE": "1.5"})
    with pytest.raises(ValueError, match="decisions_capacity"):
        load_config(env={"TPUKUBE_DECISIONS_ENABLED": "1",
                         "TPUKUBE_DECISIONS_CAPACITY": "0"})
    cfg = load_config(env={"TPUKUBE_DECISIONS_ENABLED": "1",
                           "TPUKUBE_DECISIONS_SAMPLE_RATE": "0.25"})
    assert cfg.decisions_enabled and cfg.decisions_sample_rate == 0.25

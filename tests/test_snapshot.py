"""Epoch-cached scheduling snapshots + vectorized slice-fit sweep
(ISSUE 5): placement parity of the vectorized ``find_slice`` against
the pre-change per-origin reference implementation (kept here as the
oracle), epoch invalidation at every mutation seam, cache-on vs
cache-defeated placement parity through the real webhook stack, and
the snapshot observability surface (/metrics + /statusz).

The chaos angle: scenario 8 (apiserver chaos, tests/test_chaos.py)
runs the full control plane with the snapshot cache ON and asserts
zero ledger divergence — a stale-snapshot placement would surface
there as a double-booked chip. The seam tests here prove why it
cannot: every mutation path (commit/release/upsert, reserve/rollback/
dissolve/assignment, eviction confirm, restart rebuild — torn writes
reach the ledger through commit) bumps an epoch the cache keys on.
"""

import random
import time

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import Box, MeshSpec, surface
from tpukube.core.types import (
    RESOURCE_TPU,
    AllocResult,
    ChipInfo,
    ContainerInfo,
    NodeInfo,
    PodGroup,
    PodInfo,
    ResourceList,
    TopologyCoord,
    canonical_link,
    make_device_id,
)
from tpukube.sched import slicefit
from tpukube.sched.extender import Extender
from tpukube.sched.slicefit import (
    _candidate_shapes,
    _Sweep,
    box_breaks_link,
    box_coords,
    find_slice,
    occupancy_grid,
)
from tpukube.sched.snapshot import sweep_for


# -- the oracle: the pre-change find_slice, per-origin Python loop -----------

def reference_find_slice(mesh, occupied, count=None, shape=None,
                         allow_irregular=False, broken=None):
    """The pre-vectorization implementation, verbatim in structure:
    iterate shapes in candidate order, iterate origins in lexicographic
    order, score each box with the per-box ``contact`` path, keep the
    strict minimum of (surface, -contact, origin). The vectorized
    ``find_slice`` must return byte-identical coordinates."""
    slicefit._validate_request(count, shape)
    grid = occupancy_grid(mesh, occupied)
    sweep = _Sweep(mesh, grid)
    best_key = None
    best_box = None
    tier = None
    for shp in _candidate_shapes(mesh, count, shape):
        s = surface(shp)
        if tier is not None and s > tier:
            break
        for origin in sweep.origins(shp):
            box = Box(TopologyCoord(*(int(v) for v in origin)), shp)
            if broken and box_breaks_link(mesh, box, broken):
                continue
            key = (s, -sweep.contact(box), tuple(int(v) for v in origin))
            if best_key is None or key < best_key:
                best_key, best_box, tier = key, box, s
    if best_box is not None:
        return box_coords(mesh, best_box)
    if allow_irregular and shape is None and count is not None:
        return slicefit._find_connected(mesh, grid, count, broken)
    return None


PROPERTY_MESHES = [
    MeshSpec((4, 4, 4), host_block=(2, 2, 1)),
    MeshSpec((4, 4, 1), host_block=(2, 2, 1), torus=(True, False, False)),
    MeshSpec((4, 2, 3), host_block=(1, 1, 1), torus=(True, True, True)),
    MeshSpec((2, 3, 1), host_block=(1, 1, 1), torus=(False, True, False)),
    MeshSpec((1, 4, 2), host_block=(1, 1, 1), torus=(False, True, False)),
    MeshSpec((8, 8, 2), host_block=(2, 2, 1)),
]


def test_vectorized_find_slice_matches_reference_oracle():
    """ISSUE 5 acceptance: randomized occupancy grids x request
    counts/shapes x broken-link sets — the vectorized sweep returns
    coordinates BYTE-IDENTICAL to the reference implementation."""
    rng = random.Random(1234)
    trials = 0
    for mesh in PROPERTY_MESHES:
        coords = list(mesh.all_coords())
        for _ in range(40):
            occupied = {
                c for c in coords
                if rng.random() < rng.choice([0.0, 0.2, 0.5, 0.8])
            }
            broken = set()
            if rng.random() < 0.5:
                for _ in range(rng.randint(1, 3)):
                    a = rng.choice(coords)
                    nbs = mesh.neighbors(a)
                    if nbs:
                        broken.add(canonical_link(a, rng.choice(nbs)))
            if rng.random() < 0.5:
                req = dict(count=rng.choice([1, 2, 3, 4, 6, 8, 12, 16]))
            else:
                n = rng.choice([2, 4, 8])
                shapes = _candidate_shapes(mesh, n, None)
                if not shapes:
                    continue
                req = dict(shape=tuple(rng.choice(shapes)))
            irregular = rng.random() < 0.3 and "count" in req
            got = find_slice(mesh, occupied, broken=broken or None,
                             allow_irregular=irregular, **req)
            want = reference_find_slice(
                mesh, occupied, broken=broken or None,
                allow_irregular=irregular, **req)
            assert got == want, (mesh.dims, mesh.torus, req, occupied,
                                 broken)
            trials += 1
    assert trials > 150  # the sweep above must not degenerate


def test_batched_contacts_match_per_box_contact():
    """``_Sweep.contacts`` (one integral-image gather per face per
    shape tier) must agree entry-for-entry with the per-box ``contact``
    slab path, including torus wrap, walls, and length-1/2 axes."""
    rng = random.Random(7)
    for mesh in PROPERTY_MESHES:
        coords = list(mesh.all_coords())
        occupied = set(rng.sample(coords, k=len(coords) // 3))
        sweep = _Sweep(mesh, occupancy_grid(mesh, occupied))
        shapes = {
            s for n in (1, 2, 4, 8) for s in _candidate_shapes(mesh, n, None)
        }
        for shp in shapes:
            batched = sweep.contacts(shp)
            for origin, got in zip(sweep.origins(shp), batched):
                box = Box(TopologyCoord(*(int(v) for v in origin)), shp)
                assert sweep.contact(box) == int(got), (
                    mesh.dims, mesh.torus, shp, origin)


def test_candidate_shapes_memoized():
    mesh = MeshSpec((4, 4, 4), host_block=(2, 2, 1))
    a = _candidate_shapes(mesh, 8, None)
    b = _candidate_shapes(MeshSpec((4, 4, 4), host_block=(1, 1, 1)), 8, None)
    assert a is b  # keyed on dims+request, host partition irrelevant
    assert list(a) == list(slicefit.factor_shapes(8, mesh.dims))
    assert _candidate_shapes(mesh, None, (1, 4, 2)) is _candidate_shapes(
        mesh, None, (1, 4, 2))


# -- fixtures ----------------------------------------------------------------

def _mini_extender(dims=(4, 4, 1), host_block=(2, 2, 1)):
    cfg = load_config(env={})
    mesh = MeshSpec(dims=dims, host_block=host_block)
    ext = Extender(cfg)
    for host in mesh.all_hosts():
        chips = [
            ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        ext.state.upsert_node(host, codec.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id), mesh))
    return ext, mesh, cfg


def _pod(name, tpu=1, priority=0, group=None):
    return PodInfo(name=name, priority=priority, group=group, containers=[
        ContainerInfo(name="main",
                      requests=ResourceList({RESOURCE_TPU: tpu})),
    ])


def _alloc(pod_key, node, indices, mesh, coords=None):
    return AllocResult(
        pod_key=pod_key, node_name=node,
        device_ids=[make_device_id(i) for i in indices],
        coords=coords or [mesh.coords_of_host(node)[i] for i in indices],
    )


# -- the cache proper --------------------------------------------------------

def test_snapshot_cached_until_mutation_and_counts_hits():
    ext, mesh, cfg = _mini_extender()
    snap1 = ext.snapshots.current()
    snap2 = ext.snapshots.current()
    assert snap1 is snap2  # no mutation: the SAME object, not a rebuild
    r0, h0 = ext.snapshots.rebuilds, ext.snapshots.hits
    assert h0 >= 1
    d0 = ext.snapshots.delta_applies
    ext.state.commit(_alloc("default/a", "host-0-0-0", [0, 1], mesh))
    snap3 = ext.snapshots.current()
    assert snap3 is not snap1
    # the epoch moved, so the snapshot advanced — via the O(Δ) delta
    # path (ISSUE 10), not a full rebuild
    assert ext.snapshots.delta_applies == d0 + 1
    assert ext.snapshots.rebuilds == r0
    sid = cfg.slice_id
    assert snap3.slice(sid).occupied >= {
        TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)}


def test_snapshot_slice_content_matches_direct_accessors():
    ext, mesh, cfg = _mini_extender()
    ext.state.commit(_alloc("default/a", "host-0-0-0", [0, 1, 2], mesh))
    res = ext.gang.ensure_reservation(
        _pod("g-0", tpu=1, group=PodGroup("g", min_member=4)), 1)
    assert res is not None
    sid = cfg.slice_id
    ss = ext.snapshots.current().slice(sid)
    assert ss.occupied == ext.state.occupied_coords(sid)
    assert ss.reserved == ext.gang.reserved_coords(sid)
    assert ss.unhealthy == ext.state.unhealthy_coords(sid)
    assert ss.broken == ext.state.broken_links(sid)
    assert ss.utilization == ext.state.slice_utilization(sid)
    # cached fragmentation == the grid-based wrapper's number
    assert ss.fragmentation() == pytest.approx(
        slicefit.fragmentation(mesh, ss.occupied))
    assert ss.largest_free_box() == slicefit.largest_free_box(
        mesh, occupancy_grid(mesh, ss.occupied))


# -- epoch invalidation: every mutation seam ---------------------------------

def test_ledger_seams_bump_epoch():
    ext, mesh, cfg = _mini_extender()
    epochs = [ext.state.epoch()]

    def bumped():
        epochs.append(ext.state.epoch())
        assert epochs[-1] > epochs[-2], "mutation did not bump the epoch"

    ext.state.commit(_alloc("default/a", "host-0-0-0", [0], mesh))
    bumped()
    ext.state.release("default/a")
    bumped()
    # node re-annotation (the inject_fault path: health flips arrive as
    # a NEW annotation payload through upsert_node)
    host = "host-0-0-0"
    chips = [
        ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                 hbm_bytes=cfg.hbm_bytes_per_chip)
        for i, c in enumerate(mesh.coords_of_host(host))
    ]
    from tpukube.core.types import Health

    chips[0].health = Health.UNHEALTHY
    annos = codec.annotate_node(
        NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id), mesh)
    ext.state.upsert_node(host, annos)
    bumped()
    # UNCHANGED payload: decoded view kept, epoch must NOT bump (this
    # is what keeps the cache hot across identical webhook resends)
    before = ext.state.epoch()
    snap = ext.snapshots.current()
    ext.state.upsert_node(host, annos)
    assert ext.state.epoch() == before
    assert ext.snapshots.current() is snap
    # release of an unknown pod: no mutation, no bump
    ext.state.release("default/ghost")
    assert ext.state.epoch() == before


def test_gang_seams_bump_epoch():
    ext, mesh, cfg = _mini_extender()
    sid = cfg.slice_id
    epochs = [ext.gang.epoch()]

    def bumped():
        epochs.append(ext.gang.epoch())
        assert epochs[-1] > epochs[-2], "gang mutation did not bump epoch"

    group = PodGroup("g", min_member=2)
    res = ext.gang.ensure_reservation(_pod("g-0", group=group), 1)
    bumped()
    # member assignment (the bind seam)
    coords = sorted(res.coords)[:1]
    node = ext.state.hosts_by_coord(sid)[coords[0]]
    ext.state.commit(AllocResult(
        pod_key="default/g-0", node_name=node,
        device_ids=[make_device_id(
            ext.state.node(node).index_at(coords[0]))],
        coords=list(coords),
    ))
    ext.gang.on_bound(res, "default/g-0", list(coords), node)
    bumped()
    # member release back into the pool
    ext.gang.on_release("default/g-0")
    bumped()
    # terminating-victim mask registration + eviction confirm
    ext.gang.register_terminating(
        res, {"default/v": (sid, [TopologyCoord(3, 3, 0)])})
    bumped()
    assert TopologyCoord(3, 3, 0) in ext.snapshots.current().slice(
        sid).reserved
    assert ext.gang.on_victim_gone("default/v")
    bumped()
    assert TopologyCoord(3, 3, 0) not in ext.snapshots.current().slice(
        sid).reserved
    # dissolve (preemption victim death)
    ext.gang.dissolve(res.key)
    bumped()
    # TTL rollback through the sweep
    res2 = ext.gang.ensure_reservation(_pod("h-0", group=PodGroup(
        "h", min_member=2)), 1)
    assert res2 is not None
    bumped()
    rolled = ext.gang.sweep(now=time.monotonic() + 10_000)
    assert rolled == [("default", "h")]
    bumped()


def test_restart_rebuild_bumps_epoch_and_restores_snapshot():
    ext, mesh, cfg = _mini_extender()
    alloc = _alloc("default/a", "host-0-0-0", [0, 1], mesh)
    pods = [{codec.ANNO_ALLOC: codec.encode_alloc(alloc)}]
    e0 = ext.state.epoch()
    snap0 = ext.snapshots.current()
    assert ext.rebuild_from_pods(pods) == 1
    assert ext.state.epoch() > e0
    snap1 = ext.snapshots.current()
    assert snap1 is not snap0
    assert TopologyCoord(0, 0, 0) in snap1.slice(cfg.slice_id).occupied


def test_stale_snapshot_never_served_through_webhook_cycle():
    """The integration form of the seam tests: schedule through the
    real webhook handlers and assert every placement-visible mutation
    invalidates the cache (a stale snapshot would mask or free the
    wrong chips — the scenario-8 failure mode)."""
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        sid = c.extender._config.slice_id
        _, alloc = c.schedule(c.make_pod("a", tpu=2))
        snap = c.extender.snapshots.current()
        assert set(alloc.coords) <= snap.slice(sid).occupied
        # pod completion -> lifecycle release -> chips free again
        c.complete_pod("a")
        snap2 = c.extender.snapshots.current()
        assert snap2 is not snap
        assert not set(alloc.coords) & snap2.slice(sid).occupied
        # chip fault re-annotates the node; the refreshed webhook send
        # must land in the snapshot as an unhealthy (occupied) chip
        c.inject_fault("host-0-0-0", 0)
        c.schedule(c.make_pod("b", tpu=1))
        bad = c.nodes["host-0-0-0"].chips[0].coord
        snap3 = c.extender.snapshots.current()
        assert bad in snap3.slice(sid).unhealthy
        # and the cache is actually HOT between mutations: idle reads hit
        h0 = c.extender.snapshots.hits
        c.extender.snapshots.current()
        assert c.extender.snapshots.hits == h0 + 1


# -- cache-on vs cache-defeated parity through the real stack ----------------

def _drive_workload(c):
    """A placement-sensitive sequence: burst fill, a preempting gang,
    completions, refill — every decision depends on the sweeps."""
    placements = {}
    for i in range(6):
        node, alloc = c.schedule(c.make_pod(f"burst-{i}", tpu=1,
                                            priority=0))
        placements[f"burst-{i}"] = (node, tuple(alloc.coords))
    group = PodGroup("train", min_member=4)
    for i in range(4):
        node, alloc = c.schedule(c.make_pod(
            f"train-{i}", tpu=2, priority=100, group=group))
        placements[f"train-{i}"] = (node, tuple(alloc.coords))
    c.complete_pod("burst-1")
    node, alloc = c.schedule(c.make_pod("refill-0", tpu=1))
    placements["refill-0"] = (node, tuple(alloc.coords))
    return placements


def test_placement_parity_with_cache_defeated():
    """ISSUE 5 acceptance: the epoch cache is a pure performance layer
    — the same workload scheduled with the cache defeated (invalidate
    before every lookup, i.e. the pre-change rebuild-per-webhook
    behavior) must produce IDENTICAL placements, preemptions included."""
    from tpukube.sim import SimCluster

    env = {
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }
    with SimCluster(load_config(env=env)) as c:
        cached = _drive_workload(c)
        assert c.extender.snapshots.hits > 0  # the cache really engaged
    with SimCluster(load_config(env=env)) as c:
        snaps = c.extender.snapshots
        orig = snaps.current

        def paranoid_current():
            snaps.invalidate()
            return orig()

        snaps.current = paranoid_current
        uncached = _drive_workload(c)
        assert snaps.hits == 0
    assert cached == uncached


# -- observability -----------------------------------------------------------

def test_snapshot_metrics_and_statusz_render():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    ext, mesh, cfg = _mini_extender()
    ext.state.commit(_alloc("default/a", "host-0-0-0", [0, 1], mesh))
    ext.snapshots.current()
    ext.snapshots.current()
    text = render_extender_metrics(ext)
    assert "# TYPE tpukube_snapshot_rebuilds_total counter" in text
    assert "# TYPE tpukube_snapshot_hits_total counter" in text
    assert 'tpukube_snapshot_rebuild_seconds{quantile="0.5"}' in text
    sid = cfg.slice_id
    assert f'tpukube_slice_fragmentation{{slice="{sid}"}}' in text
    assert f'tpukube_slice_largest_free_box_chips{{slice="{sid}"}}' in text
    # the rendered fragmentation is the snapshot's cached number
    ss = ext.snapshots.current().slice(sid)
    line = next(l for l in text.splitlines()
                if l.startswith("tpukube_slice_fragmentation"))
    assert float(line.split(" ")[1]) == pytest.approx(
        ss.fragmentation(), abs=1e-6)

    doc = extender_statusz(ext)
    snap = doc["snapshot"]
    assert snap["rebuilds"] >= 1 and snap["hits"] >= 1
    assert 0.0 <= snap["hit_rate"] <= 1.0
    assert snap["slices"][sid]["fragmentation"] == pytest.approx(
        round(ss.fragmentation(), 4))
    assert snap["slices"][sid]["largest_free_box"] == ss.largest_free_box()
    assert snap["epoch"]["ledger"] == ext.state.epoch()
    assert snap["epoch"]["gang"] == ext.gang.epoch()


def test_observer_lookups_do_not_inflate_hit_counters():
    """Scrape self-traffic must not mask the flat-hits diagnostic:
    /metrics and /statusz reads go through observe(), which never
    counts a hit — but a rebuild an observer performs is real work
    and still counts."""
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    ext, mesh, cfg = _mini_extender()
    ext.snapshots.current()
    h0, r0 = ext.snapshots.hits, ext.snapshots.rebuilds
    ext.snapshots.observe()
    render_extender_metrics(ext)
    extender_statusz(ext)
    assert ext.snapshots.hits == h0, "observer reads counted as hits"
    assert ext.snapshots.rebuilds == r0  # warm cache: no rebuild either
    # after a mutation, an observer-triggered advance IS counted (the
    # O(Δ) delta path serves it; a rebuild only on overflow/structural)
    d0 = ext.snapshots.delta_applies
    ext.state.commit(_alloc("default/obs", "host-1-1-0", [0], mesh))
    render_extender_metrics(ext)
    assert ext.snapshots.delta_applies == d0 + 1
    assert ext.snapshots.rebuilds == r0
    assert ext.snapshots.hits == h0
    # ...and the next scheduling lookup inherits it as a hit
    ext.snapshots.current()
    assert ext.snapshots.hits == h0 + 1


def test_sweep_for_is_the_adhoc_constructor_seam():
    """Request-specific grids (preemption, restore) build through
    snapshot.sweep_for and behave exactly like a direct sweep."""
    mesh = MeshSpec((4, 4, 1), host_block=(2, 2, 1))
    blocked = {TopologyCoord(0, 0, 0), TopologyCoord(1, 1, 0)}
    sweep = sweep_for(mesh, blocked)
    boxes = list(slicefit.iter_free_boxes_in(sweep, count=4))
    ref = list(slicefit.iter_free_boxes(
        mesh, occupancy_grid(mesh, blocked), count=4))
    assert [(b.box, b.surface, b.contact, b.origin_key) for b in boxes] \
        == [(b.box, b.surface, b.contact, b.origin_key) for b in ref]


# -- the audit sentinel (ISSUE 7) --------------------------------------------

def test_audit_off_by_default_and_validated():
    cfg = load_config(env={})
    assert cfg.snapshot_audit_rate == 0.0
    ext, _, _ = _mini_extender()
    assert ext.snapshots.audit_rate == 0.0
    ext.snapshots.current()
    ext.snapshots.current()
    assert ext.snapshots.audit_checks == 0
    with pytest.raises(ValueError):
        load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.5"})
    with pytest.raises(ValueError):
        load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "-0.1"})


def test_audit_clean_on_disciplined_mutations():
    """With every seam bumping (the shipped tree), a rate-1.0 audit
    checks every scheduling hit and finds zero divergences."""
    cfg = load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0"})
    ext = Extender(cfg)
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    for host in mesh.all_hosts():
        chips = [
            ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        ext.state.upsert_node(host, codec.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id), mesh))
    assert ext.snapshots.audit_rate == 1.0
    ext.snapshots.current()                      # rebuild
    ext.snapshots.current()                      # hit -> audited
    ext.state.commit(_alloc("d/p0", "host-0-0-0", [0], mesh))
    ext.snapshots.current()                      # rebuild (epoch moved)
    ext.snapshots.current()                      # hit -> audited
    assert ext.snapshots.audit_checks >= 2
    assert ext.snapshots.audit_divergences == 0


def test_audit_catches_a_missed_epoch_bump():
    """Simulate exactly the bug class the sentinel exists for: mutate
    the ledger, then rewind the epoch so the cache believes nothing
    changed. The next audited hit must raise SnapshotAuditError and
    count the divergence."""
    from tpukube.sched.snapshot import SnapshotAuditError

    cfg = load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0"})
    ext = Extender(cfg)
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    for host in mesh.all_hosts():
        chips = [
            ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        ext.state.upsert_node(host, codec.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id), mesh))
    ext.snapshots.current()
    # a mutation whose bump we then erase — the stale-cache heisenbug
    ext.state.commit(_alloc("d/leak", "host-0-0-0", [0], mesh))
    with ext.state._lock:
        ext.state._epoch -= 1
    with pytest.raises(SnapshotAuditError) as ei:
        ext.snapshots.current()  # hit (key unchanged) -> audit -> boom
    assert "occupied" in str(ei.value)
    assert ext.snapshots.audit_divergences == 1


def test_audit_metrics_and_statusz_render():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    cfg = load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0"})
    ext = Extender(cfg)
    text = render_extender_metrics(ext)
    assert "tpukube_snapshot_audit_checks_total" in text
    assert "tpukube_snapshot_audit_divergence_total" in text
    doc = extender_statusz(ext)
    assert doc["snapshot"]["audit"]["rate"] == 1.0
    # off by default: the audit series do NOT render (legacy exposition
    # byte-identical), but the statusz section still reports the zeros
    ext0 = Extender(load_config(env={}))
    text0 = render_extender_metrics(ext0)
    assert "tpukube_snapshot_audit" not in text0
    assert extender_statusz(ext0)["snapshot"]["audit"]["checks"] == 0


def test_audit_runs_under_the_real_webhook_stack():
    """SimCluster wiring: schedule real pods over HTTP with the
    sentinel at 1.0 — audits happen and find nothing."""
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0",
    })
    with SimCluster(cfg) as c:
        for i in range(3):
            c.schedule(c.make_pod(f"aud-{i}", tpu=1))
        c.delete_pod("aud-0")
        c.schedule(c.make_pod("aud-3", tpu=1))
        snaps = c.extender.snapshots
        assert snaps.audit_rate == 1.0
        assert snaps.audit_checks > 0
        assert snaps.audit_divergences == 0


def test_audit_via_scenarios_passthrough(monkeypatch):
    """The TPUKUBE_SNAPSHOT_AUDIT_RATE env knob reaches the canonical
    scenario configs (the acceptance drive runs scenarios 1-9 this
    way); a gang scenario under rate 1.0 reports zero divergences."""
    from tpukube.sim import scenarios

    monkeypatch.setenv("TPUKUBE_SNAPSHOT_AUDIT_RATE", "1.0")
    result = scenarios.run(4, None)  # 16-pod gang, preemption-free
    assert result["scenario"] == 4

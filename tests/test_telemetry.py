"""Fleet health telemetry + structured event journal (ISSUE 2 tentpole):
sampler transitions, per-chip /metrics series, health-summary
annotations, extender fleet rollup, event journal seams, CLI."""

import json
import urllib.request

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.types import Health, NodeInfo, PodGroup
from tpukube.obs.events import EventJournal, filter_events
from tpukube.obs.health import HealthSampler
from tpukube.sim import SimCluster


def _node_cfg(tmp_path):
    return load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })


# -- sampler -----------------------------------------------------------------

def test_sampler_detects_chip_and_link_transitions(tmp_path):
    from tpukube.device import TpuDeviceManager

    journal = EventJournal()
    with TpuDeviceManager(_node_cfg(tmp_path)) as device:
        sampler = HealthSampler(device, journal=journal, poll_seconds=999)
        assert sampler.check_once() is False  # baseline, no flip
        assert sampler.state_counts()["healthy"] == 4

        device.inject_fault(0)
        assert sampler.check_once() is True
        assert sampler.state_counts()["unhealthy"] == 1

        device.inject_link_fault((0, 0, 0), (1, 0, 0))
        assert sampler.check_once() is True
        # chip 0 stays unhealthy (dominates); chip 1 degrades
        counts = sampler.state_counts()
        assert counts["unhealthy"] == 1 and counts["degraded"] >= 1

        device.inject_fault(0, healthy=True)
        device.inject_link_fault((0, 0, 0), (1, 0, 0), up=True)
        assert sampler.check_once() is True
        assert sampler.state_counts() == {
            "healthy": 4, "degraded": 0, "unhealthy": 0,
        }

    reasons = [e["reason"] for e in journal.events()]
    assert "ChipUnhealthy" in reasons
    assert "ChipRecovered" in reasons
    assert "LinkFault" in reasons
    assert "LinkRecovered" in reasons
    # telemetry counters moved: the faulted link accumulated errors
    status = sampler.telemetry_status()
    assert status["samples"] == 4
    errs = {c["device"]: c["ici_link_errors"] for c in status["chips"]}
    assert errs["tpu-0"] >= 1 and errs["tpu-1"] >= 1


def test_plugin_metrics_carry_per_chip_series(tmp_path):
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import render_plugin_metrics
    from tpukube.obs.slo import validate_exposition
    from tpukube.plugin import DevicePluginServer

    cfg = _node_cfg(tmp_path)
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        journal = EventJournal()
        sampler = HealthSampler(device, journal=journal, poll_seconds=999)
        sampler.check_once()
        device.inject_fault(2)
        sampler.check_once()
        text = render_plugin_metrics(server, sampler=sampler,
                                     events=journal)
    # one series per chip for every telemetry family, HELP opt-in
    assert '# HELP tpukube_chip_healthy ' in text
    assert 'tpukube_chip_healthy{chip="tpu-0"} 1\n' in text
    assert 'tpukube_chip_healthy{chip="tpu-2"} 0\n' in text
    assert 'tpukube_chip_duty_cycle_percent{chip="tpu-1"}' in text
    assert 'tpukube_chip_hbm_total_bytes{chip="tpu-3"}' in text
    assert 'tpukube_chip_ici_link_errors_total{chip="tpu-0"} 0\n' in text
    assert ('tpukube_chip_health_transitions_total{chip="tpu-2"} 1\n'
            in text)
    assert 'tpukube_node_chips{state="unhealthy"} 1\n' in text
    assert 'tpukube_node_chips{state="healthy"} 3\n' in text
    assert 'tpukube_events_total{reason="ChipUnhealthy"} 1\n' in text
    # and the whole page still lints clean
    assert validate_exposition(text) == []


def test_plugin_statusz_telemetry_section(tmp_path):
    from tpukube.device import TpuDeviceManager
    from tpukube.obs.statusz import plugin_statusz
    from tpukube.plugin import DevicePluginServer

    cfg = _node_cfg(tmp_path)
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        journal = EventJournal()
        sampler = HealthSampler(device, journal=journal, poll_seconds=999)
        sampler.check_once()
        device.inject_fault(1)
        sampler.check_once()
        doc = plugin_statusz(server, device=device, sampler=sampler,
                             events=journal)
    telem = doc["telemetry"]
    assert telem["samples"] == 2
    assert telem["states"] == {"healthy": 3, "degraded": 0, "unhealthy": 1}
    by_dev = {c["device"]: c for c in telem["chips"]}
    assert by_dev["tpu-1"]["state"] == "unhealthy"
    assert by_dev["tpu-0"]["duty_cycle_avg_percent"] > 0
    assert doc["events"]["by_reason"] == {"ChipUnhealthy": 1}
    json.dumps(doc)  # whole document must stay JSON-able


# -- health-summary annotation + fleet rollup --------------------------------

def test_health_summary_annotation_roundtrip():
    from tpukube.core.types import ChipInfo, TopologyCoord, canonical_link

    chips = [
        ChipInfo("c0", 0, TopologyCoord(0, 0, 0), 1 << 30),
        ChipInfo("c1", 1, TopologyCoord(1, 0, 0), 1 << 30),
        ChipInfo("c2", 2, TopologyCoord(0, 1, 0), 1 << 30,
                 health=Health.UNHEALTHY),
    ]
    node = NodeInfo(
        name="host-0-0-0", chips=chips,
        bad_links=[canonical_link((0, 0, 0), (1, 0, 0))],
    )
    summary = codec.health_summary(node)
    assert summary["healthy"] == 0  # both healthy chips touch the link
    assert summary["degraded"] == 2
    assert summary["unhealthy"] == 1
    assert summary["badLinks"] == 1
    assert summary["chips"]["tpu-2"] == "unhealthy"
    decoded = codec.decode_health_summary(
        codec.encode_health_summary(summary)
    )
    assert decoded == summary
    # annotate_node ships both annotations together
    from tpukube.core.mesh import MeshSpec

    mesh = MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))
    annos = codec.annotate_node(node, mesh)
    assert codec.ANNO_NODE_TOPOLOGY in annos
    assert codec.ANNO_HEALTH_SUMMARY in annos


def test_extender_fleet_rollup_reflects_faults():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    from tpukube.obs.statusz import extender_statusz

    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=1))
        doc = extender_statusz(c.extender)
        assert doc["fleet"]["total"]["chips"] == 16
        assert doc["fleet"]["total"]["healthy"] == 16
        assert doc["fleet"]["degraded_slices"] == []

        c.inject_fault("host-0-0-0", 0)
        c.inject_link_fault((2, 0, 0), (3, 0, 0))
        # push the refreshed annotations the way the syncer would
        for obj in c.node_objects():
            c.extender.handle("upsert_node", {
                "name": obj["metadata"]["name"],
                "annotations": obj["metadata"]["annotations"],
            })
        doc = extender_statusz(c.extender)
        total = doc["fleet"]["total"]
        assert total["unhealthy"] == 1
        assert total["degraded"] == 2  # both endpoints of the link
        assert total["healthy"] == 13
        assert total["links_down"] == 1
        assert doc["fleet"]["degraded_slices"] == ["slice-0"]


# -- event journal -----------------------------------------------------------

def test_event_journal_dedup_ring_and_filters(tmp_path):
    sink = tmp_path / "events.jsonl"
    j = EventJournal(capacity=4, path=str(sink))
    for _ in range(3):
        j.emit("ChipUnhealthy", obj="chip/tpu-0", message="went down",
               type="Warning", node="host-0-0-0")
    j.emit("GangCommitted", obj="gang/default/g", message="4 members")
    evs = j.events()
    assert len(evs) == 2  # deduped
    assert evs[0]["count"] == 3
    assert evs[0]["last_ts"] >= evs[0]["first_ts"]
    # filters
    assert [e["reason"] for e in j.events(reason="GangCommitted")] == [
        "GangCommitted"
    ]
    assert j.events(node="host-0-0-0")[0]["reason"] == "ChipUnhealthy"
    assert j.events(node="elsewhere") == []
    assert j.counts_by_reason() == {
        "ChipUnhealthy": 3, "GangCommitted": 1,
    }
    # ring bound: flood evicts the oldest and forgets its dedup key
    for i in range(10):
        j.emit("LinkFault", obj=f"chip/tpu-{i}", message="x")
    assert len(j.events()) == 4
    j.close()
    # the sink kept every emission (count rides each line)
    from tpukube.obs import events as events_mod

    lines = events_mod.load(str(sink))
    assert len(lines) == 14
    assert filter_events(lines, reason="ChipUnhealthy")[-1]["count"] == 3


def test_event_journal_disabled_is_noop():
    j = EventJournal(capacity=0)
    assert j.emit("X", obj="y") is None
    assert j.events() == []
    assert j.stats()["enabled"] is False


def test_gang_lifecycle_emits_events():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
        reasons = c.extender.events.counts_by_reason()
        assert reasons.get("GangReserved") == 1
        assert reasons.get("GangCommitted") == 1


def test_preemption_emits_planned_executed_and_victims():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"low-{i}", tpu=1, priority=0))
        group = PodGroup("big", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"big-{i}", tpu=1, priority=100,
                                  group=group))
        reasons = c.extender.events.counts_by_reason()
        assert reasons.get("PreemptionPlanned", 0) >= 1
        assert reasons.get("PreemptionExecuted", 0) >= 1
        assert reasons.get("VictimEvicted", 0) == 4
        assert reasons.get("VictimGone", 0) == 4
        assert reasons.get("GangCommitted", 0) == 1


def test_extender_events_endpoint_filters():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
        with urllib.request.urlopen(
            f"{c.base_url}/events?reason=GangCommitted", timeout=5
        ) as r:
            evs = json.loads(r.read())
        assert len(evs) == 1
        assert evs[0]["object"] == "gang/default/g"
        with urllib.request.urlopen(
            f"{c.base_url}/events?reason=NoSuchReason", timeout=5
        ) as r:
            assert json.loads(r.read()) == []
        # /statusz carries the journal summary too
        with urllib.request.urlopen(f"{c.base_url}/statusz",
                                    timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["events"]["enabled"] is True
        assert doc["events"]["by_reason"]["GangReserved"] == 1
        assert any(e["reason"] == "GangCommitted"
                   for e in doc["events"]["recent"])


def test_events_cli_filters(tmp_path, capsys):
    from tpukube import cli

    sink = tmp_path / "events.jsonl"
    j = EventJournal(path=str(sink))
    j.emit("ChipUnhealthy", obj="chip/tpu-0", message="down",
           type="Warning", node="host-0-0-0")
    j.emit("GangCommitted", obj="gang/default/g", message="ok")
    j.emit("VictimEvicted", obj="pod/default/low-1", message="preempted",
           node="host-1-0-0")
    j.close()

    rc = cli.main_obs(["events", str(sink), "--reason", "ChipUnhealthy"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ChipUnhealthy" in out and "GangCommitted" not in out

    rc = cli.main_obs(["events", str(sink), "--pod", "default/low-1",
                       "--json"])
    assert rc == 0
    lines = [json.loads(line)
             for line in capsys.readouterr().out.splitlines()]
    assert len(lines) == 1 and lines[0]["reason"] == "VictimEvicted"

    rc = cli.main_obs(["events", str(sink), "--node", "host-0-0-0"])
    assert rc == 0
    assert "ChipUnhealthy" in capsys.readouterr().out

    # --since with a small value is relative to the newest event
    rc = cli.main_obs(["events", str(sink), "--since", "3600"])
    assert rc == 0
    assert len(capsys.readouterr().out.splitlines()) == 3


# -- the acceptance scenario -------------------------------------------------

def test_fault_telemetry_scenario_end_to_end():
    """ISSUE 2 acceptance: chip + link fault through the whole pipeline —
    node-agent per-chip series, ChipUnhealthy then ChipRecovered in the
    journal, extender fleet rollup reflecting the degraded slice, SLO
    burn rates from a live scrape."""
    from tpukube.sim import scenarios

    r = scenarios.run(7, None)
    assert r["transitions"] == {
        "chip_fault": True, "link_fault": True, "recovery": True,
    }
    assert {"ChipUnhealthy", "ChipRecovered", "LinkFault",
            "LinkRecovered"} <= set(r["event_reasons"])
    assert r["chip_series_on_node_metrics"] >= 4 * 4  # 4 chips x families
    assert r["fleet_degraded"]["unhealthy"] == 1
    assert r["fleet_degraded"]["degraded"] == 2
    assert r["fleet_degraded"]["links_down"] == 1
    assert r["fleet_recovered"]["unhealthy"] == 0
    assert r["fleet_recovered"]["degraded"] == 0
    assert r["fleet_recovered"]["healthy"] == 16
    for slo in r["slo"].values():
        assert slo["total"] > 0
        assert slo["burn_rate"] is not None
    json.dumps(r)  # one JSON-able line for tpukube-sim 7


def test_event_pod_filter_is_exact_not_substring():
    """Review regression: --pod default/p1 must not leak default/p10's
    events into the forensics."""
    j = EventJournal()
    j.emit("VictimEvicted", obj="pod/default/p1", message="a")
    j.emit("VictimEvicted", obj="pod/default/p10", message="a")
    j.emit("VictimGone", obj="pod/default/p1", message="b")
    assert [e["object"] for e in j.events(pod="default/p1")] == [
        "pod/default/p1", "pod/default/p1",
    ]
    assert [e["object"] for e in j.events(pod="default/p10")] == [
        "pod/default/p10",
    ]


def test_event_sink_rotation_caps_file_size(tmp_path):
    """Review follow-up: the event sink rotates at max_sink_bytes like
    the trace sink — a flapping chip cannot fill the disk."""
    import os

    sink = tmp_path / "events.jsonl"
    j = EventJournal(capacity=64, path=str(sink), max_sink_bytes=2048)
    for i in range(100):
        j.emit("LinkFault", obj=f"chip/tpu-{i}", message="flap")
    j.close()
    assert os.path.exists(f"{sink}.1")
    assert os.path.getsize(sink) <= 2048 + 300
    assert j.stats()["sink_rotations"] >= 1
    from tpukube.obs import events as events_mod

    assert events_mod.load(str(sink)), "live sink must still hold events"

"""BASELINE config 2: 4-pod data-parallel ResNet-50-style job, 1 TPU chip
per pod, no topology hint — multi-pod allocation fan-out through the full
stack: extender scheduling over HTTP, then each pod's Allocate executed
through a real device-plugin gRPC stack for its bound node."""

import pytest

from tpukube.core.config import load_config
from tpukube.device.tpu import ENV_KUBE_CHIP_COORDS, ENV_VISIBLE_DEVICES
from tpukube.sim import SimCluster


def test_config2_four_pod_dp_job():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(16 << 30),
    })
    with SimCluster(cfg) as cluster:
        # schedule the 4 replicas (kube Job/Deployment fan-out)
        allocs = []
        for i in range(4):
            pod = cluster.make_pod(f"resnet-dp-{i}", tpu=1)
            node, alloc = cluster.schedule(pod)
            allocs.append(alloc)
        assert cluster.utilization() == pytest.approx(4 / 16)

        # no chip double-booked anywhere
        all_coords = [c for a in allocs for c in a.coords]
        assert len(all_coords) == len(set(all_coords)) == 4

        # container-start leg: run each Allocate through a REAL plugin stack
        # (gRPC over unix sockets) on the pod's bound node
        for alloc in allocs:
            env = cluster.execute_allocation(alloc)
            assert env[ENV_VISIBLE_DEVICES] != ""
            # env coords must equal the scheduler's annotation coords
            got = {
                tuple(int(v) for v in part.split(","))
                for part in env[ENV_KUBE_CHIP_COORDS].split(";")
            }
            assert got == {tuple(c) for c in alloc.coords}

        # compute leg: the actual ResNet DP step over a 4-device mesh (one
        # device per scheduled replica), batch sharded over 'dp' — the job
        # these 4 pods exist to run
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from tpukube.workload.resnet import (
            ResNetConfig, init_params, make_dp_train_step,
        )

        rcfg = ResNetConfig(num_classes=10, width=8, stage_blocks=(1,),
                            groups=4, image_size=8)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:len(allocs)]), ("dp",))
        params = init_params(jax.random.PRNGKey(0), rcfg)
        step = make_dp_train_step(rcfg, mesh, learning_rate=0.05)
        images = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 8, 3))
        labels = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
        l0 = l = None
        for _ in range(3):
            params, loss = step(params, images, labels)
            l = float(loss)
            l0 = l if l0 is None else l0
        assert l < l0


def test_config2_without_topology_hint_still_packs_tightly():
    # DP pods carry no shape/topology hint, but topology scoring should
    # still co-locate them (fewer fragmented nodes, better for future gangs)
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as cluster:
        nodes = [cluster.schedule(cluster.make_pod(f"dp-{i}", tpu=1))[0]
                 for i in range(4)]
        # 4 single-chip pods should use at most 2 nodes under topology
        # scoring, not scatter across all 4
        assert len(set(nodes)) <= 2

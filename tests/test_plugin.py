"""End-to-end device-plugin path over real unix sockets.

This is BASELINE config 1 ("Single-pod 1-device Allocate smoke test,
fake-device sim, CPU-only control plane") plus the health-shrink flow of
SURVEY.md §4.4: Register -> ListAndWatch -> Allocate -> fault -> capacity
drop, all against a live gRPC server in-process.
"""

import pytest

from tpukube.core.config import load_config
from tpukube.device import TpuDeviceManager
from tpukube.device.tpu import ENV_HBM_LIMIT, ENV_VISIBLE_DEVICES
from tpukube.plugin import DevicePluginServer, FakeKubelet, HealthWatcher

HBM = 16 << 30


@pytest.fixture
def stack(tmp_path):
    """A running fake kubelet + plugin + health watcher on tmp sockets."""
    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    })
    with FakeKubelet(str(tmp_path)) as kubelet, \
         TpuDeviceManager(cfg, host="host-0-0-0") as device:
        with DevicePluginServer(cfg, device) as server:
            watcher = HealthWatcher(device, server, poll_seconds=60.0)
            watcher.start()
            try:
                yield cfg, kubelet, device, server, watcher
            finally:
                watcher.stop()


def test_config1_register_watch_allocate(stack):
    cfg, kubelet, device, server, watcher = stack
    server.register_with_kubelet()

    # kubelet's ListAndWatch cache fills with this host's 4 chips
    devs = kubelet.wait_for_devices("qiniu.com/tpu", 4)
    assert set(devs) == {"tpu-0", "tpu-1", "tpu-2", "tpu-3"}
    assert kubelet.allocatable("qiniu.com/tpu") == 4

    # single-pod, 1-device Allocate (the config-1 smoke)
    env = kubelet.allocate("qiniu.com/tpu", ["tpu-0"])
    assert env[ENV_VISIBLE_DEVICES] == "0"
    assert env[ENV_HBM_LIMIT] == str(HBM)
    assert server.allocation_count == 1


def test_health_fault_shrinks_allocatable(stack):
    cfg, kubelet, device, server, watcher = stack
    server.register_with_kubelet()
    kubelet.wait_for_devices("qiniu.com/tpu", 4)

    # inject an XID-analog fault; step the watcher deterministically
    device.inject_fault(2)
    assert watcher.check_once() is True
    kubelet.wait_for_health("qiniu.com/tpu", "tpu-2", "Unhealthy")
    assert kubelet.allocatable("qiniu.com/tpu") == 3

    # recovery flows back too
    device.inject_fault(2, healthy=True)
    assert watcher.check_once() is True
    kubelet.wait_for_health("qiniu.com/tpu", "tpu-2", "Healthy")
    assert kubelet.allocatable("qiniu.com/tpu") == 4
    assert watcher.transitions == 2
    # no-op poll pushes nothing
    assert watcher.check_once() is False


def test_preferred_allocation_rpc(stack):
    cfg, kubelet, device, server, watcher = stack
    server.register_with_kubelet()
    kubelet.wait_for_devices("qiniu.com/tpu", 4)
    chosen = kubelet.preferred(
        "qiniu.com/tpu", ["tpu-0", "tpu-1", "tpu-2", "tpu-3"], 2
    )
    assert len(chosen) == 2 and chosen[1] in ("tpu-1", "tpu-2")


def test_allocate_error_becomes_invalid_argument(stack):
    import grpc

    cfg, kubelet, device, server, watcher = stack
    server.register_with_kubelet()
    kubelet.wait_for_devices("qiniu.com/tpu", 4)
    with pytest.raises(grpc.RpcError) as exc:
        kubelet.allocate("qiniu.com/tpu", ["tpu-99"])
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_re_registration_replaces_stream(stack):
    cfg, kubelet, device, server, watcher = stack
    server.register_with_kubelet()
    kubelet.wait_for_devices("qiniu.com/tpu", 4)
    # plugin restarts re-register (SURVEY.md §6: stateless control plane)
    server.register_with_kubelet()
    kubelet.wait_for_devices("qiniu.com/tpu", 4)
    env = kubelet.allocate("qiniu.com/tpu", ["tpu-1"])
    assert env[ENV_VISIBLE_DEVICES] == "1"


def test_vtpu_node_advertises_shares(tmp_path):
    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SHARES_PER_CHIP": "2",
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    })
    with FakeKubelet(str(tmp_path)) as kubelet, \
         TpuDeviceManager(cfg) as device, \
         DevicePluginServer(cfg, device) as server:
        server.register_with_kubelet()
        devs = kubelet.wait_for_devices("qiniu.com/vtpu", 8)
        assert all("frac" in d for d in devs)
        env = kubelet.allocate("qiniu.com/vtpu", ["tpu-0-frac1of2"])
        assert env[ENV_HBM_LIMIT] == str(HBM // 2)


def test_kubelet_restart_triggers_reregistration(tmp_path):
    """Kubelet restart semantics: the new kubelet wipes the device-plugin
    dir (unlinking our socket) and expects a fresh Register. The
    KubeletSessionWatcher must notice both facts, rebind, and re-register
    — without it the node would advertise zero TPUs until the agent's own
    next restart."""
    import os

    from tpukube.plugin import KubeletSessionWatcher

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as device:
        server = DevicePluginServer(cfg, device)
        server.start()
        try:
            kubelet = FakeKubelet(str(tmp_path))
            kubelet.start()
            server.register_with_kubelet()
            kubelet.wait_for_devices(server.resource_name, 4)
            watch = KubeletSessionWatcher(server, poll_seconds=999)
            assert watch.check_once() is False  # steady state: no-op

            # kubelet restarts: old process gone, plugin dir wiped
            kubelet.stop()
            assert watch.check_once() is False  # kubelet down: wait
            if os.path.exists(server.socket_path):
                os.unlink(server.socket_path)  # the restart wipe
            kubelet = FakeKubelet(str(tmp_path))
            kubelet.start()

            assert watch.check_once() is True
            assert watch.reregistrations == 1
            kubelet.wait_for_devices(server.resource_name, 4)
            # allocations work through the re-registered session
            env = kubelet.allocate(server.resource_name, ["tpu-0"])
            assert env[ENV_VISIBLE_DEVICES] == "0"
            assert watch.check_once() is False  # stable again
            kubelet.stop()
        finally:
            server.stop()


def test_reregistration_retries_after_failed_register(tmp_path):
    """A kubelet whose socket exists but whose Registration service is not
    serving yet must NOT consume the restart event — the next poll retries."""
    import os

    from tpukube.plugin import KubeletSessionWatcher

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as device:
        server = DevicePluginServer(cfg, device)
        server.start()
        try:
            kubelet = FakeKubelet(str(tmp_path))
            kubelet.start()
            server.register_with_kubelet()
            watch = KubeletSessionWatcher(server, poll_seconds=999)
            kubelet.stop()
            # a DIFFERENT file appears at the kubelet socket path (new
            # inode) but nothing is serving: Register must fail...
            with open(cfg.kubelet_socket_path(), "w") as f:
                f.write("")
            with pytest.raises(Exception):
                watch.check_once()
            assert watch.reregistrations == 0
            os.unlink(cfg.kubelet_socket_path())
            # ...and once a real kubelet returns, the retry succeeds
            kubelet = FakeKubelet(str(tmp_path))
            kubelet.start()
            assert watch.check_once() is True
            assert watch.reregistrations == 1
            kubelet.wait_for_devices(server.resource_name, 4)
            kubelet.stop()
        finally:
            server.stop()


def test_socket_wipe_with_failed_register_is_retried(tmp_path):
    """Socket vanished but the kubelet identity is UNCHANGED: if the
    rebind's Register fails, the next poll sees socket-present +
    identity-equal — only separately-tracked registration state makes it
    retry instead of leaving the plugin silently unregistered."""
    import os

    from tpukube.plugin import KubeletSessionWatcher

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as device:
        server = DevicePluginServer(cfg, device)
        server.start()
        try:
            kubelet = FakeKubelet(str(tmp_path))
            kubelet.start()
            server.register_with_kubelet()
            watch = KubeletSessionWatcher(server, poll_seconds=999)
            assert watch.check_once() is False  # steady state

            os.unlink(server.socket_path)  # wipe; same kubelet stays up
            real_register = server.register_with_kubelet

            def failing_register(*a, **k):
                raise RuntimeError("registration refused")

            server.register_with_kubelet = failing_register
            with pytest.raises(RuntimeError):
                watch.check_once()
            assert os.path.exists(server.socket_path)  # rebind DID happen
            assert watch.reregistrations == 0

            server.register_with_kubelet = real_register
            # socket present, identity unchanged — must still retry
            assert watch.check_once() is True
            assert watch.reregistrations == 1
            kubelet.wait_for_devices(server.resource_name, 4)
            kubelet.stop()
        finally:
            server.stop()

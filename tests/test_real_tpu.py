"""Real-TPU legs of BASELINE config 1 (SURVEY.md §5 point 1).

This machine has one real TPU chip behind a tunnel; TPU init can take
minutes on first touch, so these tests are OPT-IN: set
``TPUKUBE_TEST_REAL_TPU=1`` to run them (the driver's bench exercises the
real chip every round regardless). They prove the two real-hardware
claims: the native layer's ``real`` backend enumerates the local chip via
libtpu, and the env a tpukube Allocate injects actually steers a JAX
process (visible devices + a jitted computation on the TPU).
"""

import os
import subprocess
import sys

import pytest

REAL = os.environ.get("TPUKUBE_TEST_REAL_TPU") == "1"
skip_unless_real = pytest.mark.skipif(
    not REAL, reason="set TPUKUBE_TEST_REAL_TPU=1 to run real-chip tests"
)


@skip_unless_real
def test_real_backend_enumerates_local_chip():
    """Whichever path produced the inventory — PJRT runtime introspection
    or the liveness+table fallback — the chips must be well-formed; the
    id naming is asserted per source, not hard-wired to the fallback."""
    from tpukube.native import TpuInfo

    with TpuInfo("real") as ti:
        chips = ti.chips()
        assert len(chips) >= 1
        assert chips[0].hbm_bytes > 0
        source = ti.source()
        if source == "pjrt":
            # runtime-reported: <kind>-<device id>, never the table's
            # synthetic "local-" prefix
            assert not chips[0].chip_id.startswith("local-")
            assert chips[0].num_cores >= 1
        else:
            assert source.startswith("table (")
            assert chips[0].chip_id.startswith("local-")


@skip_unless_real
def test_allocated_env_drives_real_jax_compute():
    """Allocate env -> subprocess with the REAL platform -> jitted matmul
    on the TPU. Run in a subprocess because this test session pins
    JAX_PLATFORMS=cpu (conftest) and JAX platform choice is
    process-global."""
    from tpukube.core.config import load_config
    from tpukube.device import TpuDeviceManager

    cfg = load_config(env={
        "TPUKUBE_BACKEND": "real",
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm:
        env = dm.allocate_env(["tpu-0"])
    child_env = dict(os.environ)
    # undo the conftest's CPU pinning for this child, and keep the
    # virtual-device XLA flag out of the real-chip process. This machine's
    # chip rides the "axon" PJRT plugin, loaded from the machine's
    # original PYTHONPATH — so APPEND the repo, never replace.
    child_env["JAX_PLATFORMS"] = os.environ.get("TPUKUBE_REAL_PLATFORM", "axon")
    child_env["XLA_FLAGS"] = " ".join(
        f for f in child_env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    child_env.update(env)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prior = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = f"{repo}:{prior}" if prior else repo
    code = (
        "import jax, jax.numpy as jnp\n"
        "devs = jax.devices()\n"
        "assert devs and devs[0].platform != 'cpu', devs\n"
        "x = jnp.ones((256, 256), jnp.bfloat16)\n"
        "y = jax.jit(lambda a: (a @ a).sum())(x)\n"
        "print('REAL_TPU_OK', float(y), devs[0].device_kind)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=child_env,
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "REAL_TPU_OK" in out.stdout

"""core/retry.py — the unified retry/backoff/circuit layer (ISSUE 4).

Everything here is deterministic: clocks, sleeps, and RNGs are
injected, so the policy math and the breaker's state machine are
asserted exactly, not statistically.
"""

from __future__ import annotations

import random

import pytest

from tpukube.core import retry
from tpukube.core.config import load_config
from tpukube.obs.events import EventJournal


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_retrier(policy, **kw):
    sleeps: list[float] = []
    clock = kw.pop("clock", FakeClock())
    r = retry.Retrier(
        policy, name=kw.pop("name", "test"),
        sleep=sleeps.append, clock=clock,
        rng=kw.pop("rng", random.Random(7)), **kw,
    )
    return r, sleeps, clock


# -- policy math -------------------------------------------------------------

def test_delay_is_exponential_and_capped():
    p = retry.RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.0)
    rng = random.Random(0)
    assert p.delay(1, rng) == pytest.approx(0.1)
    assert p.delay(2, rng) == pytest.approx(0.2)
    assert p.delay(3, rng) == pytest.approx(0.4)
    assert p.delay(10, rng) == pytest.approx(1.0)  # capped


def test_delay_jitter_only_shrinks_and_is_seeded():
    p = retry.RetryPolicy(base_delay=1.0, max_delay=1.0, jitter=0.5)
    a = [p.delay(1, random.Random(42)) for _ in range(3)]
    b = [p.delay(1, random.Random(42)) for _ in range(3)]
    assert a == b  # same seed, same jitter
    for d in a:
        assert 0.5 <= d <= 1.0  # full-jitter shrinks, never grows


def test_backoff_sequence_grows_and_resets():
    b = retry.Backoff(base=1.0, cap=8.0, jitter=0.0)
    assert [b.next() for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    assert b.failures == 5
    b.reset()
    assert b.failures == 0
    assert b.next() == 1.0


# -- Retrier -----------------------------------------------------------------

def test_retrier_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("boom")
        return "ok"

    r, sleeps, _ = make_retrier(
        retry.RetryPolicy(max_attempts=5, base_delay=0.1, jitter=0.0,
                          deadline=0)
    )
    assert r.call(flaky) == "ok"
    assert len(calls) == 3
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]
    assert r.last_attempts == 3
    assert r.stats.attempts == 3
    assert r.stats.retries == 2
    assert r.stats.exhausted == 0


def test_retrier_exhausts_max_attempts_and_journals():
    journal = EventJournal(capacity=16)
    r, sleeps, _ = make_retrier(
        retry.RetryPolicy(max_attempts=3, base_delay=0.1, jitter=0.0,
                          deadline=0),
        journal=journal,
    )
    with pytest.raises(OSError):
        r.call(lambda: (_ for _ in ()).throw(OSError("down")))
    assert len(sleeps) == 2  # 3 attempts = 2 sleeps
    assert r.stats.exhausted == 1
    evs = journal.events(reason="RetryExhausted")
    assert len(evs) == 1 and "3 attempt" in evs[0]["message"]


def test_retrier_honors_overall_deadline():
    clock = FakeClock()
    r, sleeps, clock = make_retrier(
        retry.RetryPolicy(max_attempts=100, base_delay=1.0, max_delay=1.0,
                          jitter=0.0, deadline=2.5),
        clock=clock,
    )

    def failing():
        clock.advance(1.0)  # each attempt burns a second
        raise OSError("slow failure")

    with pytest.raises(OSError):
        r.call(failing)
    # attempt 1 (t=1) + sleep 1 -> attempt 2 (t=2): next sleep would
    # land past the 2.5s deadline, so it gives up at 2 attempts
    assert r.last_attempts == 2
    assert r.stats.exhausted == 1


def test_retrier_does_not_retry_non_retryable():
    r, sleeps, _ = make_retrier(retry.RetryPolicy(max_attempts=5))
    with pytest.raises(KeyError):
        r.call(lambda: (_ for _ in ()).throw(KeyError("logic bug")))
    assert sleeps == []
    assert r.stats.exhausted == 0  # a logic error is not "exhausted"


def test_retrier_custom_classifier():
    r, sleeps, _ = make_retrier(
        retry.RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0,
                          deadline=0),
        retryable=lambda e: isinstance(e, ValueError),
    )
    with pytest.raises(OSError):
        r.call(lambda: (_ for _ in ()).throw(OSError("not retryable here")))
    assert sleeps == []


# -- CircuitBreaker ----------------------------------------------------------

def make_breaker(threshold=3, reset=10.0, probes=1, journal=None):
    clock = FakeClock()
    cb = retry.CircuitBreaker(
        failure_threshold=threshold, reset_seconds=reset,
        name="t", half_open_probes=probes, clock=clock, journal=journal,
    )
    return cb, clock


def test_breaker_opens_after_consecutive_failures():
    journal = EventJournal(capacity=16)
    cb, clock = make_breaker(threshold=3, journal=journal)
    for _ in range(2):
        cb.on_failure()
    assert cb.state() == retry.CLOSED
    cb.on_success()  # success resets the consecutive count
    for _ in range(2):
        cb.on_failure()
    assert cb.state() == retry.CLOSED
    cb.on_failure()
    assert cb.state() == retry.OPEN
    assert cb.opens == 1
    assert cb.is_open()
    with pytest.raises(retry.CircuitOpenError):
        cb.before_call()
    assert journal.events(reason="CircuitOpen")


def test_breaker_half_open_probe_closes_on_success():
    journal = EventJournal(capacity=16)
    cb, clock = make_breaker(threshold=1, reset=10.0, journal=journal)
    cb.on_failure()
    assert cb.state() == retry.OPEN
    clock.advance(10.0)
    assert cb.state() == retry.HALF_OPEN
    assert not cb.is_open()  # half-open admits a probe: not refusing
    cb.before_call()  # the probe is admitted
    with pytest.raises(retry.CircuitOpenError):
        cb.before_call()  # probe budget (1) exhausted
    cb.on_success()
    assert cb.state() == retry.CLOSED
    assert journal.events(reason="CircuitClosed")


def test_breaker_half_open_probe_failure_reopens():
    cb, clock = make_breaker(threshold=1, reset=10.0)
    cb.on_failure()
    clock.advance(10.0)
    cb.before_call()  # probe
    cb.on_failure()   # probe failed
    assert cb.state() == retry.OPEN
    assert cb.opens == 2
    clock.advance(5.0)
    with pytest.raises(retry.CircuitOpenError):
        cb.before_call()  # fresh reset window, still open


def test_breaker_disabled_at_zero_threshold():
    cb, _ = make_breaker(threshold=0)
    for _ in range(100):
        cb.on_failure()
    assert cb.state() == retry.CLOSED
    cb.before_call()  # never refuses
    assert cb.opens == 0
    assert not cb.enabled


def test_breaker_state_codes():
    cb, clock = make_breaker(threshold=1, reset=1.0)
    assert cb.state_code() == 0
    cb.on_failure()
    assert cb.state_code() == 2
    clock.advance(1.0)
    assert cb.state_code() == 1


def test_retrier_with_circuit_fails_fast_once_open():
    cb, _ = make_breaker(threshold=2, reset=10.0)
    r, sleeps, _ = make_retrier(
        retry.RetryPolicy(max_attempts=10, base_delay=0.01, jitter=0.0,
                          deadline=0),
        circuit=cb,
    )
    calls = []

    def failing():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(retry.CircuitOpenError):
        r.call(failing)
    # two real attempts tripped the breaker; the third admission was
    # refused without touching the target — no 10-attempt hammering
    assert len(calls) == 2
    assert cb.opens == 1


def test_retrier_non_retryable_answers_do_not_trip_circuit():
    """A dependency that ANSWERS (409 conflicts, 404s) is healthy: a
    streak of logical errors must never open the circuit and push the
    extender into degraded mode."""
    cb, _ = make_breaker(threshold=2)
    r, _, _ = make_retrier(
        retry.RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0,
                          deadline=0),
        retryable=lambda e: isinstance(e, OSError),
        circuit=cb,
    )
    for _ in range(5):
        with pytest.raises(ValueError):
            r.call(lambda: (_ for _ in ()).throw(ValueError("409-shaped")))
    assert cb.state() == retry.CLOSED
    assert cb.opens == 0


def test_aborted_probe_releases_the_half_open_slot():
    """An interrupted probe (BaseException) must not wedge the breaker
    half-open with its budget consumed forever."""
    cb, clock = make_breaker(threshold=1, reset=10.0)
    cb.on_failure()
    clock.advance(10.0)
    with pytest.raises(KeyboardInterrupt):
        cb.call(lambda: (_ for _ in ()).throw(KeyboardInterrupt()))
    assert cb.state() == retry.HALF_OPEN
    cb.before_call()  # the slot was released: a new probe is admitted
    cb.on_success()
    assert cb.state() == retry.CLOSED


def test_retrier_aborted_probe_releases_the_slot():
    cb, clock = make_breaker(threshold=1, reset=10.0)
    r, _, _ = make_retrier(retry.RetryPolicy(max_attempts=3), circuit=cb)
    cb.on_failure()
    clock.advance(10.0)
    with pytest.raises(KeyboardInterrupt):
        r.call(lambda: (_ for _ in ()).throw(KeyboardInterrupt()))
    assert cb.state() == retry.HALF_OPEN
    cb.before_call()  # admitted: no leaked probe slot


def test_breaker_call_wrapper_counts_outcomes():
    cb, _ = make_breaker(threshold=2)
    assert cb.call(lambda: "fine") == "fine"
    with pytest.raises(OSError):
        cb.call(lambda: (_ for _ in ()).throw(OSError("x")))
    with pytest.raises(OSError):
        cb.call(lambda: (_ for _ in ()).throw(OSError("x")))
    assert cb.state() == retry.OPEN


# -- config knobs ------------------------------------------------------------

def test_policy_from_config_defaults():
    cfg = load_config(env={})
    p = retry.policy_from_config(cfg)
    assert p.max_attempts == 5
    assert p.base_delay == pytest.approx(0.1)
    assert p.max_delay == pytest.approx(5.0)
    assert p.jitter == pytest.approx(0.5)
    assert p.deadline == pytest.approx(30.0)
    # circuits ship DISABLED: chaos off by default
    assert cfg.circuit_failure_threshold == 0
    assert cfg.chaos_seed == 0


def test_config_retry_knobs_load_and_coerce():
    cfg = load_config(env={
        "TPUKUBE_RETRY_MAX_ATTEMPTS": "7",
        "TPUKUBE_RETRY_BASE_DELAY_SECONDS": "0.25",
        "TPUKUBE_RETRY_JITTER": "0.1",
        "TPUKUBE_RETRY_ATTEMPT_TIMEOUT_SECONDS": "2.5",
        "TPUKUBE_CIRCUIT_FAILURE_THRESHOLD": "4",
        "TPUKUBE_CIRCUIT_RESET_SECONDS": "12",
        "TPUKUBE_CHAOS_SEED": "99",
    })
    assert cfg.retry_max_attempts == 7
    assert cfg.retry_base_delay_seconds == pytest.approx(0.25)
    assert cfg.retry_jitter == pytest.approx(0.1)
    assert cfg.circuit_failure_threshold == 4
    assert cfg.circuit_reset_seconds == pytest.approx(12.0)
    assert cfg.chaos_seed == 99
    p = retry.policy_from_config(cfg)
    assert p.attempt_timeout == pytest.approx(2.5)


def test_attempt_timeout_caps_rest_transport_timeout():
    """The per-attempt deadline actually reaches the transport: a hung
    attempt burns at most attempt_timeout of the overall deadline."""
    from tpukube.apiserver import RestApiServer

    cfg = load_config(env={
        "TPUKUBE_RETRY_ATTEMPT_TIMEOUT_SECONDS": "2.5",
    })
    api = RestApiServer(
        base_url="http://127.0.0.1:1", token="t",
        retrier=retry.Retrier(retry.policy_from_config(cfg),
                              name="apiserver"),
    )
    assert api._timeout == pytest.approx(2.5)
    # 0 = keep the transport default
    api2 = RestApiServer(
        base_url="http://127.0.0.1:1", token="t",
        retrier=retry.Retrier(retry.RetryPolicy(), name="apiserver"),
    )
    assert api2._timeout == pytest.approx(10.0)


@pytest.mark.parametrize("env", [
    {"TPUKUBE_RETRY_MAX_ATTEMPTS": "0"},
    {"TPUKUBE_RETRY_BASE_DELAY_SECONDS": "0"},
    {"TPUKUBE_RETRY_MAX_DELAY_SECONDS": "-1"},
    {"TPUKUBE_RETRY_MAX_DELAY_SECONDS": "0.01"},  # < base_delay
    {"TPUKUBE_RETRY_JITTER": "1.0"},
    {"TPUKUBE_RETRY_JITTER": "-0.1"},
    {"TPUKUBE_RETRY_DEADLINE_SECONDS": "-5"},
    {"TPUKUBE_RETRY_ATTEMPT_TIMEOUT_SECONDS": "-1"},
    {"TPUKUBE_CIRCUIT_FAILURE_THRESHOLD": "-1"},
    {"TPUKUBE_CIRCUIT_RESET_SECONDS": "0"},
    {"TPUKUBE_CIRCUIT_HALF_OPEN_PROBES": "0"},
    {"TPUKUBE_CHAOS_SEED": "-1"},
])
def test_config_rejects_bad_retry_knobs(env):
    with pytest.raises(ValueError):
        load_config(env=env)

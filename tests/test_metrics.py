"""Metrics endpoints: extender /metrics (aiohttp) + node-agent MetricsServer."""

import urllib.error
import urllib.request

from tpukube.core.config import load_config
from tpukube.device import TpuDeviceManager
from tpukube.metrics import MetricsServer, quantile, render_plugin_metrics
from tpukube.plugin import DevicePluginServer
from tpukube.sim import SimCluster


def test_quantile_nearest_rank():
    assert quantile([], 0.5) == 0.0
    assert quantile([3.0], 0.99) == 3.0
    assert quantile([1, 2, 3, 4, 5], 0.5) == 3
    assert quantile([1, 2, 3, 4, 5], 0.0) == 1
    assert quantile([1, 2, 3, 4, 5], 1.0) == 5


def test_extender_metrics_endpoint():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=2))
        with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "tpu_chip_utilization_percent 50" in text
        assert "tpukube_binds_total 1" in text
        assert 'tpukube_webhook_latency_seconds{handler="bind",quantile="0.5"}' in text


def test_plugin_metrics_server(tmp_path):
    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
         DevicePluginServer(cfg, device) as server:
        ms = MetricsServer(lambda: render_plugin_metrics(server))
        ms.start()
        try:
            device.inject_fault(1)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert 'tpukube_plugin_devices{health="Healthy"} 3' in text
            assert 'tpukube_plugin_devices{health="Unhealthy"} 1' in text
            assert 'resource="qiniu.com/tpu"' in text
            # non-metrics path 404s
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{ms.port}/x", timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            ms.stop()


def test_plugin_metrics_export_round2_loops(tmp_path):
    """VERDICT round-2 task 4: the loop counters operators alarm on —
    inventory source, intent depth, divergences, health transitions,
    kubelet re-registrations — all appear on /metrics."""
    from types import SimpleNamespace

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        server.intents.put("default/p0", ["tpu-0"])
        server.divergences = 3
        health = SimpleNamespace(transitions=2)
        kubelet_watch = SimpleNamespace(reregistrations=1)
        text = render_plugin_metrics(
            server, health=health, kubelet_watch=kubelet_watch
        )
        assert 'tpukube_plugin_inventory_source{source="sim"} 1' in text
        assert "tpukube_plugin_intent_depth 1" in text
        assert "tpukube_plugin_divergences_total 3" in text
        assert "tpukube_plugin_health_transitions_total 2" in text
        assert "tpukube_plugin_reregistrations_total 1" in text


def test_extender_metrics_export_reconcile_and_evictions():
    """The extender's /metrics tells the divergence/reconcile/eviction
    story end to end when the daemon loops are attached."""
    import json as _json

    from tpukube.apiserver import (
        AllocReconcileLoop, EvictionExecutor, FakeApiServer,
    )
    from tpukube.sched.extender import make_app
    from tpukube.sim.harness import _AppThread, _free_port

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = FakeApiServer()
        reconcile = AllocReconcileLoop(c.extender, api, poll_seconds=999)
        evictions = EvictionExecutor(c.extender, api, poll_seconds=999)
        reconcile.reconciled = 5
        evictions.evicted, evictions.blocked, evictions.failures = 7, 1, 2
        c.extender.pending_evictions.append("default/x")

        port = _free_port()
        app = _AppThread(
            make_app(c.extender, reconcile=reconcile, evictions=evictions),
            "127.0.0.1", port,
        )
        app.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
        finally:
            app.stop()
        assert "tpukube_evictions_pending 1" in text
        assert "tpukube_evictions_total 7" in text
        assert "tpukube_evictions_blocked_total 1" in text
        assert "tpukube_eviction_failures_total 2" in text
        assert "tpukube_reconciles_total 5" in text
        c.extender.pending_evictions.clear()


def test_extender_metrics_export_round5_loops():
    """VERDICT round-4 task 4: a dead release watch must be VISIBLE —
    lifecycle releases, node refreshes, victim-termination gauge, and
    eviction age all appear on /metrics when the daemon loops are
    attached (exactly what cli.main_extender passes to make_app)."""
    from tpukube.apiserver import (
        EvictionExecutor, FakeApiServer, NodeTopologyRefreshLoop,
        PodLifecycleReleaseLoop,
    )
    from tpukube.sched.extender import make_app
    from tpukube.sim.harness import _AppThread, _free_port

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = FakeApiServer()
        evictions = EvictionExecutor(c.extender, api, poll_seconds=999)
        node_refresh = NodeTopologyRefreshLoop(c.extender, api,
                                               poll_seconds=999)
        lifecycle = PodLifecycleReleaseLoop(
            c.extender, api, poll_seconds=999, use_watch=False,
            evictions=evictions,
        )
        node_refresh.refreshed = 3
        lifecycle.released = 9

        port = _free_port()
        app = _AppThread(
            make_app(c.extender, evictions=evictions,
                     node_refresh=node_refresh, lifecycle=lifecycle),
            "127.0.0.1", port,
        )
        app.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
        finally:
            app.stop()
        assert "tpukube_node_refreshes_total 3" in text
        assert "tpukube_lifecycle_releases_total 9" in text
        assert "tpukube_gang_victims_terminating 0" in text
        assert "tpukube_eviction_oldest_age_seconds 0" in text


def test_plugin_metrics_export_intent_watch(tmp_path):
    """The intent watcher's watch-events counter reaches the node agent's
    /metrics (a flat counter while pods bind = steering is dead)."""
    from types import SimpleNamespace

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        text = render_plugin_metrics(
            server, intent_watch=SimpleNamespace(watch_events=6)
        )
        assert "tpukube_plugin_intent_watch_events_total 6" in text


def test_syncer_metrics_render():
    from types import SimpleNamespace

    from tpukube.metrics import render_syncer_metrics

    text = render_syncer_metrics(SimpleNamespace(syncs=4))
    assert "tpukube_syncer_syncs_total 4" in text


def test_label_values_escaped():
    """Arbitrary runtime text in label values (inventory_source carries
    PJRT error strings) must not corrupt the exposition format."""
    from tpukube.metrics import _fmt

    line = _fmt("m", 1, {"source": 'table (err "quoted"\nline\\x)'})
    assert line == 'm{source="table (err \\"quoted\\"\\nline\\\\x)"} 1\n'

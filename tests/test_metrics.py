"""Metrics endpoints: extender /metrics (aiohttp) + node-agent MetricsServer."""

import urllib.error
import urllib.request

from tpukube.core.config import load_config
from tpukube.device import TpuDeviceManager
from tpukube.metrics import MetricsServer, quantile, render_plugin_metrics
from tpukube.plugin import DevicePluginServer
from tpukube.sim import SimCluster


def test_quantile_nearest_rank():
    assert quantile([], 0.5) == 0.0
    assert quantile([3.0], 0.99) == 3.0
    assert quantile([1, 2, 3, 4, 5], 0.5) == 3
    assert quantile([1, 2, 3, 4, 5], 0.0) == 1
    assert quantile([1, 2, 3, 4, 5], 1.0) == 5


def test_extender_metrics_endpoint():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=2))
        with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "tpu_chip_utilization_percent 50" in text
        assert "tpukube_binds_total 1" in text
        assert 'tpukube_webhook_latency_seconds{handler="bind",quantile="0.5"}' in text


def test_plugin_metrics_server(tmp_path):
    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
         DevicePluginServer(cfg, device) as server:
        ms = MetricsServer(lambda: render_plugin_metrics(server))
        ms.start()
        try:
            device.inject_fault(1)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert 'tpukube_plugin_devices{health="Healthy"} 3' in text
            assert 'tpukube_plugin_devices{health="Unhealthy"} 1' in text
            assert 'resource="qiniu.com/tpu"' in text
            # non-metrics path 404s
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{ms.port}/x", timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            ms.stop()

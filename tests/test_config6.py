"""Config 6 — steady-state churn (VERDICT round-4 task 2).

The asserting twin of tpukube.sim.scenarios.churn: pods FINISH (terminal
phase, objects linger — the real-cluster shape), the pod-lifecycle
release loop frees their chips with no manual release anywhere, and
replacements schedule into the freed capacity. What config 5 proves for
arrival, this proves for steady state: utilization returns to full after
every wave, nothing leaks, the committed gang is untouched.
"""

import pytest

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster


@pytest.fixture(scope="module")
def churned():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("train", min_member=16)
        for i in range(16):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        for i in range(16):
            c.schedule(c.make_pod(f"burst-{i}", tpu=1))
        assert c.utilization() == 1.0

        samples = []
        n = 16
        for wave in range(4):
            done = [f"burst-{i}" for i in range(wave * 4, wave * 4 + 4)]
            for name in done:
                c.complete_pod(name)
            samples.append(("after_complete", c.utilization()))
            for _ in done:
                c.schedule(c.make_pod(f"burst-{n}", tpu=1))
                n += 1
            samples.append(("after_refill", c.utilization()))
        yield c, samples


def test_completions_release_through_lifecycle_loop(churned):
    c, _ = churned
    # every completed pod's ledger entry is gone, released by the loop
    # observing the terminal phase — the pod OBJECTS still exist
    for i in range(16):
        assert c.extender.state.allocation(f"default/burst-{i}") is None
        assert f"default/burst-{i}" in c.pods, "object must linger"
    assert c._lifecycle.released == 16


def test_utilization_recovers_every_wave(churned):
    c, samples = churned
    dips = [u for tag, u in samples if tag == "after_complete"]
    refills = [u for tag, u in samples if tag == "after_refill"]
    assert all(u == 1.0 for u in refills), (
        "utilization failed to recover after a churn wave — release "
        f"leak: {samples}"
    )
    # the dip is exactly the completed chips, not more (no over-release)
    assert all(abs(u - (1.0 - 4 / 32)) < 1e-9 for u in dips), samples


def test_committed_gang_untouched_by_churn(churned):
    c, _ = churned
    res = c.extender.gang.reservation("default", "train")
    assert res is not None and res.committed
    assert len(res.assigned) == 16
    for i in range(16):
        assert c.extender.state.allocation(f"default/train-{i}") is not None


def test_churn_scenario_emits_stability_metrics():
    """The operator-facing scenario (tpukube-sim 6 / bench.py) reports
    the numbers BASELINE.md records: min-after-refill utilization and
    re-schedule latency quantiles."""
    from tpukube.sim import scenarios

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    out = scenarios.churn(cfg)
    assert out["util_min_after_refill_percent"] == 100.0
    assert out["lifecycle_releases"] == out["waves"] * out["wave_size"]
    assert 0 < out["resched_p50_s"] <= out["resched_p99_s"]

"""ISSUE 19: fleet elasticity — graceful drain/decommission, the
drain/health-check race fix, the journal durability barrier, the
absent-chip geometry mask, and the elasticity property test.

The acceptance gates covered here:
  * off-is-off: default config constructs neither coordinator, and
    nothing drain- or autoscaler-shaped reaches /metrics or /statusz;
    with the flags on the added series are EXACTLY the declared
    elasticity families;
  * choreography: cordon -> budgeted migrate-or-preempt -> un-ingest,
    with the disruption budget enforced per tick, `drain_evict`
    provenance on every evicted pod, and cancel() restoring cordons;
  * the absent mask: a slice that lost a host (spot churn, partial
    un-ingest) must not advertise the departed chips as free in any
    sweep or capacity count — and the audit sentinel agrees;
  * capacity forensics: demand stranded ONLY by an in-flight drain
    classifies "draining", never "capacity";
  * the journal sync() barrier: records enqueued before sync() survive
    a crash immediately after it returns;
  * drain intent on the sharded plane: a draining subprocess replica
    is never dead-marked by the health checker (the race fix);
  * the property test: >= 200 seeded random interleavings of
    {cordon, migrate, crash, restart, heal, un-ingest} with the ledger
    snapshot equal to a from-scratch rebuild after every step.
"""

from __future__ import annotations

import random

import pytest

from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.metrics import render_extender_metrics
from tpukube.obs.slo import parse_metrics
from tpukube.obs.statusz import extender_statusz
from tpukube.sched import kube, slicefit
from tpukube.sched.snapshot import _audit_divergence
from tpukube.sim.harness import SimCluster


def drain_config(**extra: str):
    return load_config(env={
        "TPUKUBE_DRAIN_ENABLED": "1",
        **extra,
    })


def two_slices(dims=(4, 4, 1)) -> dict[str, MeshSpec]:
    return {
        sid: MeshSpec(dims=dims, host_block=(2, 2, 1))
        for sid in ("s0", "s1")
    }


def slice_nodes(c: SimCluster, sid: str) -> list[str]:
    return sorted(n for n in c.extender.state.node_names()
                  if c.extender.state.slice_of_node(n) == sid)


def _drive(c: SimCluster, drain) -> int:
    """Tick a drain to completion; returns ticks taken."""
    ticks = 0
    while drain.active():
        drain.tick()
        c.clock.advance(1.0) if isinstance(c.clock, FakeClock) else None
        ticks += 1
        assert ticks < 50, "drain failed to converge"
    return ticks


# -- off-is-off / exposition -------------------------------------------------

def test_drain_off_is_off():
    """Default config: no coordinator, no autoscaler, and nothing
    elasticity-shaped reaches /metrics or /statusz."""
    with SimCluster(load_config(env={}), clock=FakeClock()) as c:
        c.schedule(c.make_pod("a", tpu=1))
        assert c.extender.drain is None
        assert c.extender.autoscaler is None
        text = render_extender_metrics(c.extender)
        assert "tpukube_drain" not in text
        assert "tpukube_autoscaler" not in text
        doc = extender_statusz(c.extender)
        assert "drain" not in doc
        assert "autoscaler" not in doc


def test_drain_on_adds_exactly_the_declared_families():
    """Flags on add the drain + autoscaler series — and ONLY them, so
    the off exposition stays byte-identical by construction."""
    def series_names(enabled: bool) -> set[str]:
        env = {}
        if enabled:
            env = {"TPUKUBE_DRAIN_ENABLED": "1",
                   "TPUKUBE_AUTOSCALE_ENABLED": "1"}
        with SimCluster(load_config(env=env), clock=FakeClock()) as c:
            c.schedule(c.make_pod("a", tpu=1))
            return {s.name for s in
                    parse_metrics(render_extender_metrics(c.extender))}

    off, on = series_names(False), series_names(True)
    assert off <= on
    assert on - off == {
        "tpukube_drain_started_total",
        "tpukube_drain_completed_total",
        "tpukube_drain_evictions_total",
        "tpukube_drain_nodes_removed_total",
        "tpukube_drain_chips_removed_total",
        "tpukube_drain_slices_dropped_total",
        "tpukube_drain_peak_tick_moves",
        "tpukube_drain_active",
        "tpukube_autoscaler_scale_ups_total",
        "tpukube_autoscaler_scale_downs_total",
        "tpukube_autoscaler_nodes_added_total",
        "tpukube_autoscaler_ticks_total",
    }


def test_autoscale_requires_drain():
    with pytest.raises(ValueError, match="requires drain_enabled"):
        load_config(env={"TPUKUBE_AUTOSCALE_ENABLED": "1"})


# -- the choreography --------------------------------------------------------

def test_drain_choreography_cordon_migrate_uningest():
    """End to end on a two-slice fleet: residents of the draining
    slice are evicted under budget, survivors on the other slice are
    untouched, the nodes un-ingest, the empty slice drops — and the
    snapshot audit agrees with a from-scratch rebuild throughout."""
    cfg = drain_config(TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES="2")
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        ext = c.extender
        placed: dict[str, str] = {}
        for i in range(8):
            node, _ = c.schedule(c.make_pod(f"p{i}", tpu=2))
            placed[f"default/p{i}"] = node
        doomed = slice_nodes(c, "s0")
        residents = [k for k, n in placed.items() if n in doomed]
        assert residents, "expected residents on s0"
        drain_id = ext.drain.begin(doomed, reason="firmware")
        # phase 1: cordoned, still serving, out of placement sweeps
        assert sorted(ext.state.cordoned_nodes()) == doomed
        assert all(ext.state.allocation(k) is not None
                   for k in residents)
        snap = ext.snapshots.current()
        assert snap.slice("s0").cordoned
        # phase 2+3: budgeted migration, then un-ingest
        _drive(c, ext.drain)
        assert ext.drain.peak_tick_moves <= 2
        for k in residents:
            assert ext.state.allocation(k) is None
        for k in set(placed) - set(residents):
            assert ext.state.allocation(k) is not None
        assert ext.state.slice_ids() == ["s1"]
        assert not ext.state.cordoned_nodes()
        ext.snapshots.audit_now()
        st = ext.drain.statusz()
        assert st["completed"] == 1
        assert st["nodes_removed_total"] == len(doomed)
        assert st["active"] == []
        assert drain_id == "drain-1"


def test_drain_evict_provenance_stage():
    """Every evicted resident's decision chain gains a drain_evict
    stage naming WHICH drain took the chips."""
    cfg = drain_config(TPUKUBE_DECISIONS_ENABLED="1")
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        ext = c.extender
        node, _ = c.schedule(c.make_pod("victim", tpu=4))
        sid = ext.state.slice_of_node(node)
        drain_id = ext.drain.begin(slice_nodes(c, sid), reason="mx")
        _drive(c, ext.drain)
        evs = [e for e in ext.decisions.events()
               if e.get("pod") == "default/victim"]
        stages = [e.get("stage") for e in evs]
        assert "drain_evict" in stages
        evict = [e for e in evs if e.get("stage") == "drain_evict"][0]
        assert evict["drain"] == drain_id
        assert evict["node"] == node


def test_drain_budget_bounds_each_tick():
    """drain_max_concurrent_moves workloads per tick, never more —
    the disruption budget the runbook promises."""
    cfg = drain_config(TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES="2")
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        ext = c.extender
        for i in range(16):
            c.schedule(c.make_pod(f"p{i}", tpu=1))
        doomed = slice_nodes(c, "s0")
        n_resident = sum(1 for a in ext.state.allocations()
                         if a.node_name in set(doomed))
        assert n_resident > 2
        ext.drain.begin(doomed)
        per_tick = []
        while ext.drain.active():
            per_tick.append(ext.drain.tick())
            assert len(per_tick) < 50
        assert max(per_tick) <= 2
        assert sum(per_tick) == n_resident
        assert ext.drain.peak_tick_moves <= 2


def test_drain_cancel_restores_cordons():
    cfg = drain_config()
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        c._sync_nodes()
        ext = c.extender
        doomed = slice_nodes(c, "s0")
        drain_id = ext.drain.begin(doomed)
        assert sorted(ext.state.cordoned_nodes()) == doomed
        assert ext.drain.cancel(drain_id) is True
        assert not ext.state.cordoned_nodes()
        assert not ext.drain.active()
        assert ext.drain.cancel(drain_id) is False  # idempotent
        # the fleet is whole again: a full-slice gang still fits
        node, _ = c.schedule(c.make_pod("after", tpu=4))
        assert node


def test_cordoned_nodes_leave_placement_sweeps():
    """While a drain is in flight nothing NEW lands on its nodes —
    placements route to the other slice."""
    cfg = drain_config()
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        c._sync_nodes()
        ext = c.extender
        ext.drain.begin(slice_nodes(c, "s0"))
        for i in range(4):
            node, _ = c.schedule(c.make_pod(f"p{i}", tpu=2))
            assert ext.state.slice_of_node(node) == "s1"


# -- the absent-chip geometry mask -------------------------------------------

def test_absent_chips_never_read_as_free():
    """A slice that lost one host (spot churn / partial un-ingest)
    must shrink in every sweep and count: the departed chips are
    phantom capacity otherwise (a 16-chip gang 'fitting' a 12-chip
    slice). The audit sentinel must agree with the masked build."""
    with SimCluster(load_config(env={}), clock=FakeClock(),
                    slices=two_slices()) as c:
        c._sync_nodes()
        ext = c.extender
        victim = slice_nodes(c, "s0")[0]
        out = ext.state.remove_nodes([victim])
        assert out["removed"] == [victim]
        snap = ext.snapshots.current()
        ss = snap.slice("s0")
        assert len(ss.absent) == 4
        assert ss.free_chips == 12
        assert ss.blocked_free_chips == 12
        assert slicefit.find_slice_in(ss.blocked_sweep(),
                                      count=16) is None
        assert slicefit.find_slice_in(
            snap.slice("s1").blocked_sweep(), count=16) is not None
        ext.snapshots.audit_now()
        # live placements keep working around the hole
        placed = 0
        for i in range(12):
            try:
                node, _ = c.schedule(c.make_pod(f"p{i}", tpu=4))
                placed += 1
            except Exception:
                break
        assert placed >= 7  # 12 chips on s0 can hold at most 3 more


def test_absent_mask_survives_delta_advance():
    """Ledger deltas after the removal carry the absent set through
    the O(Δ) path untouched — and still match the rebuild oracle."""
    with SimCluster(load_config(env={}), clock=FakeClock(),
                    slices=two_slices()) as c:
        c._sync_nodes()
        ext = c.extender
        ext.state.remove_nodes([slice_nodes(c, "s0")[0]])
        ext.snapshots.current()
        c.schedule(c.make_pod("a", tpu=1))  # a plain ledger delta
        snap = ext.snapshots.current()
        assert len(snap.slice("s0").absent) == 4
        fresh = ext.snapshots._build(snap.key)
        assert _audit_divergence(snap, fresh) == []


# -- capacity forensics: the "draining" reason --------------------------------

def test_capacity_draining_reason():
    """Demand stranded ONLY by an in-flight drain classifies
    'draining' with the fits-if-uncordoned slice named — wait out the
    drain, don't buy capacity."""
    cfg = drain_config(TPUKUBE_CAPACITY_ENABLED="1")
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        ext = c.extender
        # fill one slice completely; drain the other — the only place
        # a full-slice ask could go is the capacity mid-drain
        full = {ext.state.slice_of_node(
            c.schedule(c.make_pod(f"f{i}", tpu=4))[0])
            for i in range(4)}
        assert len(full) == 1, "fillers should pack one slice"
        draining = ({"s0", "s1"} - full).pop()
        ext.drain.begin(slice_nodes(c, draining))
        pod = kube.pod_from_k8s(c.make_pod("ask", tpu=16))
        ext.capacity.note_failed_plan(pod)
        counts = ext.capacity.unschedulable_counts()
        assert counts == {"draining": 1}
        detail = ext.capacity.stranded_by_reason()
        assert detail["draining"] == (1, 16)


# -- the journal durability barrier ------------------------------------------

def test_journal_sync_barrier_survives_crash(tmp_path):
    """Records enqueued before sync() returns are on disk even if the
    process dies immediately after — the begin()/complete contract the
    drain choreography relies on."""
    from tpukube.sched.journal import StateJournal, load_wal

    path = str(tmp_path / "wal.jsonl")
    j = StateJournal(path, fsync="always")
    j.note("cordon", {"n": ["host-a"], "c": True})
    j.note("unnodes", {"n": ["host-a"]})
    j.sync()
    j.crash()  # queued-but-undrained records are dropped BY DESIGN
    records, info = load_wal(path)
    assert [r["k"] for r in records] == ["cordon", "unnodes"]
    assert info == {"torn": 0, "bad_crc": 0}


def test_journal_sync_after_close_is_a_noop(tmp_path):
    from tpukube.sched.journal import StateJournal

    j = StateJournal(str(tmp_path / "wal.jsonl"), fsync="off")
    j.close()
    j.sync()  # must neither raise nor hang


def test_drain_cordon_durable_across_crash(tmp_path):
    """begin() returns only after the cordon seam is durable: a crash
    right after begin() recovers KNOWING which capacity was leaving."""
    cfg = drain_config(
        TPUKUBE_JOURNAL_ENABLED="1",
        TPUKUBE_JOURNAL_PATH=str(tmp_path / "wal.jsonl"),
        TPUKUBE_JOURNAL_FSYNC="always",
    )
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        c._sync_nodes()
        doomed = slice_nodes(c, "s0")
        c.extender.drain.begin(doomed, reason="maintenance")
        c.crash_extender()
        c.restart_extender()
        assert c.last_recovery["mode"] == "warm"  # journal recovery
        assert sorted(c.extender.state.cordoned_nodes()) == doomed
        c.extender.snapshots.audit_now()


# -- drain intent vs the health checker (sharded plane) ----------------------

def _can_spawn_workers() -> bool:
    from tpukube.sched.shard import ShardError, SubprocessTransport
    try:
        probe = SubprocessTransport(0, load_config(env={}),
                                    fake_clock=False)
        probe.close()
        return True
    except (ShardError, OSError):
        return False


@pytest.mark.skipif(not _can_spawn_workers(),
                    reason="cannot spawn shard-worker subprocesses here")
def test_drain_intent_shields_replica_from_dead_marking():
    """The race fix: a replica mid-drain is slow, not dead. With drain
    intent registered the health checker skips it — even when the
    probe would fail — and dead-marks it only after the intent
    clears."""
    cfg = load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_BATCH_ENABLED": "1",
    })
    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True,
                    slices=two_slices(dims=(2, 2, 2))) as c:
        router = c.extender
        victim = 0
        router.register_drain_intent(victim)
        # intent surfaces on /statusz while the replica still serves
        assert router.replicas[victim].name in (
            router.statusz().get("drain_intent") or [])
        router.replicas[victim].transport._proc.kill()
        router.replicas[victim].transport._proc.wait(timeout=10)
        clock.advance(1.0)
        skips0 = router.health_skips_draining_total
        assert router.health_check() == 0
        assert router.replicas[victim].alive
        assert router.health_skips_draining_total == skips0 + 1
        router.clear_drain_intent(victim)
        clock.advance(1.0)
        assert router.health_check() == 1
        assert not router.replicas[victim].alive


# -- the elasticity property test --------------------------------------------

class _ElasticityDriver:
    """Random-walk driver over the elasticity seams: cordon, heal
    (uncordon), migrate (budgeted drain ticks), un-ingest, crash +
    restart. After every step the cached snapshot must equal a
    from-scratch ledger rebuild — phantom capacity, lost cordons, and
    stale absent masks all fail here."""

    def __init__(self, c: SimCluster, rng: random.Random):
        self.c, self.rng = c, rng
        c._sync_nodes()
        self.ext = c.extender
        self.pod_n = 0

    def _nodes(self) -> list[str]:
        return sorted(self.ext.state.node_names())

    def op_commit(self):
        self.pod_n += 1
        try:
            self.c.schedule(self.c.make_pod(f"e{self.pod_n}", tpu=1))
        except Exception:
            pass  # fleet full/cordoned everywhere right now

    def op_release(self):
        allocs = sorted(a.pod_key for a in self.ext.state.allocations())
        if not allocs:
            return
        key = self.rng.choice(allocs)
        ns, name = key.split("/", 1)
        self.c.complete_pod(name, namespace=ns)

    def op_cordon(self):
        nodes = self._nodes()
        if not nodes:
            return
        pick = self.rng.sample(nodes, k=min(2, len(nodes)))
        self.ext.state.set_cordon(pick, True)

    def op_heal(self):
        cordoned = sorted(self.ext.state.cordoned_nodes())
        if not cordoned:
            return
        self.ext.state.set_cordon(
            [self.rng.choice(cordoned)], False)

    def op_migrate(self):
        """A budgeted drain tick over whatever is cordoned (the real
        choreography path, including complete+un-ingest when empty)."""
        cordoned = sorted(self.ext.state.cordoned_nodes())
        if not cordoned:
            return
        if not self.ext.drain.active():
            self.ext.drain.begin(cordoned, reason="storm")
        self.ext.drain.tick()

    def op_uningest(self):
        """Spot churn: rip out one alloc-free node with no notice."""
        live = {a.node_name for a in self.ext.state.allocations()}
        idle = [n for n in self._nodes() if n not in live]
        if not idle:
            return
        victim = self.rng.choice(idle)
        out = self.ext.state.remove_nodes([victim])
        if victim in out["removed"]:
            self.c.forget_nodes([victim])

    def op_crash_restart(self):
        self.c.crash_extender()
        self.c.restart_extender()
        self.ext = self.c.extender

    def step(self):
        op = self.rng.choice([
            self.op_commit, self.op_commit, self.op_commit,
            self.op_release, self.op_release,
            self.op_cordon, self.op_heal,
            self.op_migrate, self.op_migrate,
            self.op_uningest,
            self.op_crash_restart,
        ])
        op()
        snap = self.ext.snapshots.current()
        fresh = self.ext.snapshots._build(snap.key)
        diffs = _audit_divergence(snap, fresh)
        assert diffs == [], \
            f"after {op.__name__}: ledger != rebuild: {diffs}"


@pytest.mark.parametrize("seed", [11, 4242])
def test_property_elasticity_interleavings(seed, tmp_path):
    """>= 200 random steps of {cordon, migrate, crash, restart, heal,
    un-ingest} on a journaled two-slice fleet: the ledger snapshot
    equals a from-scratch rebuild after EVERY step, and the fleet
    converges to zero cordons once the dust settles."""
    cfg = drain_config(
        TPUKUBE_JOURNAL_ENABLED="1",
        TPUKUBE_JOURNAL_PATH=str(tmp_path / f"wal-{seed}.jsonl"),
    )
    with SimCluster(cfg, clock=FakeClock(),
                    slices=two_slices()) as c:
        driver = _ElasticityDriver(c, random.Random(seed))
        for _ in range(200):
            driver.step()
            c.clock.advance(1.0)
        ext = c.extender
        # settle: cancel/complete whatever is still mid-flight
        for _ in range(30):
            if not ext.drain.active():
                break
            ext.drain.tick()
            c.clock.advance(1.0)
        ext.snapshots.audit_now()

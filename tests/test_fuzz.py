"""Seeded lifecycle fuzzer: random op sequences over the real control
plane, ledger invariants checked after every step.

The fixed scenarios (configs 1-6, stress) cover the designed paths; this
drives RANDOM interleavings of the full op vocabulary — solo and gang
arrivals, completions, deletions, chip and ICI-link faults and repairs,
eviction drains — and asserts after every single op that the invariants
the whole framework exists to keep actually hold. Seeds are fixed, so a
failure reproduces exactly (print the seed + step in the assert).
"""

import random

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.types import Health, PodGroup, TopologyCoord
from tpukube.sim import SimCluster

# failure texts schedule() may legitimately produce under random load;
# ANY other error (StateError, GangError, codec, HTTP 5xx...) is a bug
# the fuzzer must surface, not swallow
EXPECTED_SCHED_FAILURES = ("unschedulable", "bind error after",
                           "cannot preempt", "no victim set",
                           "no contiguous")

SEEDS = [7, 42, 99, 512, 1234, 4242, 31337, 99991, 424243, 999331]
STEPS = 120


def _handle_sched_failure(c: SimCluster, ctx: str, e: RuntimeError,
                          attempted) -> None:
    """Shared failure classification for every fuzz loop: legitimate
    unschedulability unwinds the attempted (never-bound) pod from the
    store; anything else is an internal scheduler error the fuzzer must
    surface."""
    if not any(t in str(e) for t in EXPECTED_SCHED_FAILURES):
        raise AssertionError(f"{ctx}: internal scheduler error: {e}") from e
    if attempted is not None:
        c.pods.pop(f"default/{attempted}", None)


def _invariants(c: SimCluster, ctx: str) -> None:
    state = c.extender.state
    gang = c.extender.gang
    allocs = state.allocations()
    reservations = gang.snapshot()
    assigned_keys = {pk for res in reservations for pk in res.assigned}

    # 1. no chip coord is allocated to two whole-chip pods, and share
    # accounting never exceeds capacity
    seen: dict[tuple, str] = {}
    for a in allocs:
        view = state.node(a.node_name)
        assert view is not None, f"{ctx}: alloc on unknown node {a}"
        for co in a.coords:
            key = (view.info.slice_id, tuple(co))
            if view.shares_per_chip == 1:
                assert key not in seen, (
                    f"{ctx}: chip {key} held by {seen[key]} AND {a.pod_key}"
                )
            seen[key] = a.pod_key
    for name in state.node_names():
        view = state.node(name)
        for chip in view.info.chips:
            used = view.used_share_count(chip.index)
            assert 0 <= used <= view.shares_per_chip, (
                f"{ctx}: {name} chip {chip.index} uses {used} shares"
            )

    # 2. the ledger agrees with an INDEPENDENT oracle: the pod store's
    # own alloc annotations. Every bound, non-terminal pod not awaiting
    # eviction must account for exactly the ledger's used shares (a
    # leak shows as ledger>store, a lost release as store>ledger).
    awaiting = set(c.extender.pending_evictions)
    awaiting |= set(c._evictions._terminating)
    used_expect = 0
    for key, pod in c.pods.items():
        if key in awaiting:
            continue  # released in the ledger, eviction not yet executed
        if (pod.get("status") or {}).get("phase") in ("Succeeded",
                                                      "Failed"):
            continue  # released by the lifecycle loop; object lingers
        if not (pod.get("spec") or {}).get("nodeName"):
            continue  # never bound
        payload = (pod["metadata"].get("annotations") or {}).get(
            codec.ANNO_ALLOC)
        if not payload:
            continue
        alloc = codec.decode_alloc(payload)
        view = state.node(alloc.node_name)
        for did in alloc.device_ids:
            from tpukube.core.types import parse_device_id
            index, _ = parse_device_id(did)
            if view is not None and view.chip(index).health is Health.HEALTHY:
                used_expect += 1  # fuzz nodes are whole-chip (1 share)
    total = sum(
        1
        for name in state.node_names()
        for chip in state.node(name).info.chips
        if chip.health is Health.HEALTHY
    )
    expect = used_expect / total if total else 0.0
    assert state.utilization() == pytest.approx(expect), (
        f"{ctx}: ledger utilization {state.utilization():.4f} != "
        f"store-derived {expect:.4f}"
    )

    # 3. committed gangs are all-or-nothing: every assigned member's
    # ledger entry exists, and assignments stay within the reservation
    for res in reservations:
        for pod_key, (sid, coords) in res.assigned.items():
            assert state.allocation(pod_key) is not None, (
                f"{ctx}: gang {res.key} member {pod_key} assigned but "
                "not in ledger"
            )
            assert set(coords) <= res.slice_coords[sid], (
                f"{ctx}: member {pod_key} outside reservation"
            )
        if res.committed:
            assert len(res.assigned) >= 1, ctx

    # 4. reserved/terminating masks never overlap a DIFFERENT pod's
    # ledger allocation (a bystander bound onto a masked chip)
    for sid in state.slice_ids():
        masked = gang.reserved_coords(sid)
        for a in allocs:
            if state.slice_of_node(a.node_name) != sid:
                continue
            if a.pod_key in assigned_keys:
                continue  # gang members legitimately sit inside boxes
            for co in a.coords:
                assert TopologyCoord.of(co) not in masked, (
                    f"{ctx}: {a.pod_key} allocated on masked chip {co}"
                )


def _run_fuzz(seed: int) -> None:
    rng = random.Random(seed)
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_RESERVATION_TTL_SECONDS": "30",
    })
    with SimCluster(cfg) as c:
        live: list[str] = []       # schedulable pod names placed so far
        gangs = 0
        counter = 0
        down_links: list[tuple] = []
        sick: list[tuple[str, int]] = []

        for step in range(STEPS):
            ctx = f"seed={seed} step={step}"
            op = rng.choices(
                ["solo", "gang", "complete", "delete", "fault", "heal",
                 "link_down", "link_up", "drain"],
                weights=[30, 8, 18, 12, 6, 6, 4, 4, 12],
            )[0]
            attempted = None  # pod whose schedule() may fail mid-op
            try:
                if op == "solo":
                    name = attempted = f"s-{counter}"
                    counter += 1
                    c.schedule(c.make_pod(
                        name, tpu=rng.choice([1, 1, 1, 2, 4]),
                        priority=rng.choice([0, 5, 10]),
                    ))
                    live.append(name)
                elif op == "gang":
                    gangs += 1
                    size = rng.choice([4, 8])
                    group = PodGroup(f"g{gangs}", min_member=size)
                    prio = rng.choice([10, 100])
                    for i in range(size):
                        name = attempted = f"g{gangs}-{i}"
                        c.schedule(c.make_pod(name, tpu=1, group=group,
                                              priority=prio))
                        # appended per-bind: a mid-gang failure leaves
                        # the bound members live until TTL rollback
                        live.append(name)
                elif op == "complete" and live:
                    name = live.pop(rng.randrange(len(live)))
                    c.complete_pod(name)
                elif op == "delete" and live:
                    name = live.pop(rng.randrange(len(live)))
                    c.delete_pod(name)
                elif op == "fault":
                    node = rng.choice(sorted(c.nodes))
                    chip = rng.randrange(4)
                    c.inject_fault(node, chip)
                    sick.append((node, chip))
                elif op == "heal" and sick:
                    node, chip = sick.pop(rng.randrange(len(sick)))
                    c.inject_fault(node, chip, healthy=True)
                elif op == "link_down":
                    mesh = c.mesh
                    a = TopologyCoord(rng.randrange(4), rng.randrange(4),
                                      rng.randrange(2))
                    nbs = sorted(mesh.neighbors(a))
                    b = nbs[rng.randrange(len(nbs))]
                    c.inject_link_fault(a, b)
                    down_links.append((a, b))
                elif op == "link_up" and down_links:
                    a, b = down_links.pop(rng.randrange(len(down_links)))
                    c.inject_link_fault(a, b, up=True)
                elif op == "drain":
                    c.drain_evictions()
            except RuntimeError as e:
                _handle_sched_failure(c, ctx, e, attempted)
            # evicted pods (preemption/rollback) leave the store: drop
            # them from the live list so complete/delete target real pods
            live = [n for n in live if f"default/{n}" in c.pods]
            _invariants(c, ctx)

        # final: drain everything and the world is still consistent
        c.drain_evictions()
        _invariants(c, f"seed={seed} final")


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_lifecycle_invariants(seed):
    _run_fuzz(seed)


def _vtpu_invariants(c: SimCluster, ctx: str) -> None:
    """Share-granular oracle for the vTPU fuzz: per-chip used shares in
    the ledger must equal the store-side count of fractional ids held by
    bound, non-terminal pods."""
    from tpukube.core.types import parse_device_id

    state = c.extender.state
    expect: dict[tuple[str, int], int] = {}
    for key, pod in c.pods.items():
        if (pod.get("status") or {}).get("phase") in ("Succeeded",
                                                      "Failed"):
            continue
        if not (pod.get("spec") or {}).get("nodeName"):
            continue
        payload = (pod["metadata"].get("annotations") or {}).get(
            codec.ANNO_ALLOC)
        if not payload:
            continue
        alloc = codec.decode_alloc(payload)
        for did in alloc.device_ids:
            index, frac = parse_device_id(did)
            # mirrors the ledger's weighting rule: a fractional id is 1
            # share, a whole-chip id consumes the node's full share count
            node_view = c.extender.state.node(alloc.node_name)
            whole = (node_view.shares_per_chip
                     if node_view is not None else 1)
            weight = 1 if frac is not None else whole
            k = (alloc.node_name, index)
            expect[k] = expect.get(k, 0) + weight
    for name in state.node_names():
        view = state.node(name)
        for chip in view.info.chips:
            used = view.used_share_count(chip.index)
            assert used == expect.get((name, chip.index), 0), (
                f"{ctx}: {name} chip {chip.index} ledger says {used} "
                f"shares, store says {expect.get((name, chip.index), 0)}"
            )
            assert used <= view.shares_per_chip, ctx


@pytest.mark.parametrize("seed", [11, 2718, 314159])
def test_fuzz_vtpu_share_accounting(seed):
    """Random vTPU share churn: fractional pods arrive, complete, and
    are deleted on a 2-shares-per-chip cluster; after every op the
    ledger's per-chip share counts must equal the store-side truth (a
    re-minted live share id or a leaked share fails here)."""
    rng = random.Random(seed)
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SHARES_PER_CHIP": "2",
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"}, vtpu_shares=2) as c:
        live: list[str] = []
        counter = 0
        for step in range(100):
            ctx = f"vtpu seed={seed} step={step}"
            op = rng.choices(["add", "complete", "delete"],
                             weights=[50, 25, 25])[0]
            attempted = None  # only the failing ADD's pod is unwound
            try:
                if op == "add":
                    name = attempted = f"v-{counter}"
                    counter += 1
                    c.schedule(c.make_pod(
                        name, vtpu=rng.choice([1, 1, 2])))
                    live.append(name)
                elif op == "complete" and live:
                    c.complete_pod(live.pop(rng.randrange(len(live))))
                elif op == "delete" and live:
                    c.delete_pod(live.pop(rng.randrange(len(live))))
            except RuntimeError as e:
                _handle_sched_failure(c, ctx, e, attempted)
            _vtpu_invariants(c, ctx)


@pytest.mark.parametrize("seed", [21, 777, 480000])
def test_fuzz_dcn_gang_churn(seed):
    """Random churn on a TWO-slice (DCN) cluster with gangs that may
    split across slices: solos and allow-dcn gangs arrive, pods complete
    and vanish, evictions drain — the same invariants hold after every
    op, now spanning slice-local coordinate spaces."""
    from tpukube.core.mesh import MeshSpec

    rng = random.Random(seed)
    slices = {"slice-a": MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1)),
              "slice-b": MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))}
    cfg = load_config(env={"TPUKUBE_RESERVATION_TTL_SECONDS": "30"})
    with SimCluster(cfg, slices=slices) as c:
        live: list[str] = []
        gangs = 0
        counter = 0
        for step in range(100):
            ctx = f"dcn seed={seed} step={step}"
            op = rng.choices(
                ["solo", "gang", "complete", "delete", "drain"],
                weights=[30, 12, 22, 14, 12],
            )[0]
            attempted = None
            try:
                if op == "solo":
                    name = attempted = f"s-{counter}"
                    counter += 1
                    c.schedule(c.make_pod(name, tpu=1,
                                          priority=rng.choice([0, 5])))
                    live.append(name)
                elif op == "gang":
                    gangs += 1
                    # 6 chips never fit one 4-chip slice: forces the
                    # DCN split whenever the gang lands at all
                    size = rng.choice([3, 6])
                    group = PodGroup(f"g{gangs}", min_member=size,
                                     allow_dcn=True)
                    prio = rng.choice([10, 100])
                    for i in range(size):
                        name = attempted = f"g{gangs}-{i}"
                        c.schedule(c.make_pod(name, tpu=1, group=group,
                                              priority=prio))
                        live.append(name)
                elif op == "complete" and live:
                    c.complete_pod(live.pop(rng.randrange(len(live))))
                elif op == "delete" and live:
                    c.delete_pod(live.pop(rng.randrange(len(live))))
                elif op == "drain":
                    c.drain_evictions()
            except RuntimeError as e:
                _handle_sched_failure(c, ctx, e, attempted)
            live = [n for n in live if f"default/{n}" in c.pods]
            _invariants(c, ctx)
        c.drain_evictions()
        _invariants(c, f"dcn seed={seed} final")

"""Gang scheduling unit + lifecycle tests (SURVEY.md C10, §9.3)."""

import threading

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup, TopologyCoord
from tpukube.sim import SimCluster


def _cfg(ttl="30"):
    return load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_RESERVATION_TTL_SECONDS": ttl,
    })


def test_gang_all_members_land_contiguous():
    with SimCluster(_cfg()) as c:
        group = PodGroup("job", min_member=8)
        allocs = [
            c.schedule(c.make_pod(f"j-{i}", tpu=1, group=group))[1]
            for i in range(8)
        ]
        coords = sorted(co for a in allocs for co in a.coords)
        assert len(set(coords)) == 8
        # contiguity: the 8 chips form an axis-aligned box (2x4 or 4x2)
        xs = {x for x, y, z in coords}
        ys = {y for x, y, z in coords}
        assert len(xs) * len(ys) == 8
        res = c.extender.gang.reservation("default", "job")
        assert res.committed
        assert res.commit_latency is not None and res.commit_latency < 5


def test_gang_blocks_non_gang_poaching():
    with SimCluster(_cfg()) as c:
        group = PodGroup("big", min_member=12)
        # first member reserves a 12-chip slice; 16-chip mesh leaves 4
        c.schedule(c.make_pod("g-0", tpu=1, group=group))
        # a non-gang pod must not take reserved chips: only 4 remain
        taken = []
        for i in range(4):
            _, a = c.schedule(c.make_pod(f"solo-{i}", tpu=1))
            taken.append(a.coords[0])
        res = c.extender.gang.reservation("default", "big")
        assert not (set(taken) & res.coords)
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule(c.make_pod("solo-4", tpu=1))
        # and the gang can still finish assembling
        for i in range(1, 12):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
        assert c.extender.gang.reservation("default", "big").committed


def test_gang_ttl_rollback_releases_everything():
    with SimCluster(_cfg(ttl="0.2")) as c:
        group = PodGroup("doomed", min_member=8)
        for i in range(3):  # only 3 of 8 members ever arrive
            c.schedule(c.make_pod(f"d-{i}", tpu=1, group=group))
        assert c.utilization() == pytest.approx(3 / 16)
        import time
        time.sleep(0.3)
        rolled = c.extender.gang.sweep()
        assert ("default", "doomed") in rolled
        # all-or-nothing: the partial members' chips are free again
        assert c.utilization() == 0.0
        assert c.extender.gang.rollbacks == 1
        # the whole mesh is schedulable again
        _, a = c.schedule(c.make_pod("after", tpu=4))
        assert len(a.device_ids) == 4


def test_gang_fault_in_reserved_slice_rolls_back():
    with SimCluster(_cfg()) as c:
        group = PodGroup("fragile", min_member=8)
        _, a0 = c.schedule(c.make_pod("f-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "fragile")
        # kill an UNASSIGNED chip inside the reserved slice
        victim = sorted(res.unassigned_coords())[0]
        node = c.mesh.host_of(victim)
        index = next(
            ch.index for ch in c.nodes[node].chips if ch.coord == victim
        )
        c.inject_fault(node, index)
        # next scheduling interaction sweeps and rolls the gang back;
        # re-reservation then happens on healthy chips only
        _, a1 = c.schedule(c.make_pod("f-1", tpu=1, group=group))
        assert c.extender.gang.rollbacks == 1
        res2 = c.extender.gang.reservation("default", "fragile")
        assert victim not in res2.coords
        # f-0 was rolled back (all-or-nothing) and must be rescheduled
        assert c.extender.state.allocation("default/f-0") is None
        c.schedule(c.make_pod("f-0b", tpu=1, group=group))
        for i in range(2, 8):
            c.schedule(c.make_pod(f"f-{i}", tpu=1, group=group))
        assert res2.committed


def test_gang_shape_hint_honored():
    with SimCluster(_cfg()) as c:
        group = PodGroup("shaped", min_member=4, shape=(4, 1, 1))
        allocs = [
            c.schedule(c.make_pod(f"s-{i}", tpu=1, group=group))[1]
            for i in range(4)
        ]
        coords = sorted(co for a in allocs for co in a.coords)
        # a 4x1 (or 1x4) line, not a 2x2 square
        xs = {x for x, y, z in coords}
        ys = {y for x, y, z in coords}
        assert sorted([len(xs), len(ys)]) == [1, 4]


def test_gang_unreservable_when_fragmented():
    with SimCluster(_cfg()) as c:
        # occupy one chip per host: no contiguous 8-slice left... each host
        # block is 2x2; taking one chip per host leaves L-shapes
        for i in range(4):
            c.schedule(c.make_pod(f"frag-{i}", tpu=1))
        # actually topology packing may co-locate; occupy explicitly instead
        used = {tuple(a.coords[0]) for a in c.extender.state.allocations()}
        group = PodGroup("wide", min_member=14)  # needs 14 contiguous chips
        with pytest.raises(RuntimeError, match="no contiguous"):
            c.schedule(c.make_pod("w-0", tpu=1, group=group))


def test_gang_member_loss_before_commit_reopens_slot():
    with SimCluster(_cfg()) as c:
        group = PodGroup("churn", min_member=4)
        c.schedule(c.make_pod("m-0", tpu=1, group=group))
        c.schedule(c.make_pod("m-1", tpu=1, group=group))
        c.delete_pod("m-1")  # member dies during assembly
        res = c.extender.gang.reservation("default", "churn")
        assert len(res.assigned) == 1 and not res.committed
        # replacement + remaining members commit the gang
        c.schedule(c.make_pod("m-1b", tpu=1, group=group))
        c.schedule(c.make_pod("m-2", tpu=1, group=group))
        c.schedule(c.make_pod("m-3", tpu=1, group=group))
        assert res.committed


def test_concurrent_gang_assembly():
    with SimCluster(_cfg()) as c:
        group = PodGroup("par", min_member=16)
        errs, allocs = [], []
        def run(i):
            try:
                allocs.append(c.schedule(c.make_pod(f"p-{i}", tpu=1, group=group)))
            except Exception as e:
                errs.append(str(e))
        ts = [threading.Thread(target=run, args=(i,)) for i in range(16)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs, errs
        coords = [tuple(co) for _, a in allocs for co in a.coords]
        assert len(coords) == len(set(coords)) == 16
        assert c.extender.gang.reservation("default", "par").committed
        assert c.utilization() == 1.0


def test_overflow_replicas_schedule_as_normal_pods():
    # replicas beyond min_member must not wedge Pending forever
    with SimCluster(_cfg()) as c:
        group = PodGroup("elastic", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"e-{i}", tpu=1, group=group))
        assert c.extender.gang.reservation("default", "elastic").committed
        # two extra replicas of the same group: plain placement on free chips
        for i in range(8, 10):
            node, alloc = c.schedule(c.make_pod(f"e-{i}", tpu=1, group=group))
            assert len(alloc.device_ids) == 1
        assert c.utilization() == pytest.approx(10 / 16)


def test_committed_gang_teardown_frees_capacity():
    # regression: a committed reservation must not mask chips forever
    with SimCluster(_cfg()) as c:
        group = PodGroup("done", min_member=16)
        for i in range(16):
            c.schedule(c.make_pod(f"t-{i}", tpu=1, group=group))
        assert c.utilization() == 1.0
        for i in range(16):
            c.delete_pod(f"t-{i}")
        assert c.utilization() == 0.0
        assert c.extender.gang.reservation("default", "done") is None
        # the whole mesh is schedulable again, including a fresh full gang
        g2 = PodGroup("next", min_member=16)
        for i in range(16):
            c.schedule(c.make_pod(f"n-{i}", tpu=1, group=g2))
        assert c.utilization() == 1.0


def test_rollback_masks_member_chips_until_eviction_confirmed():
    """A rolled-back member's containers may still be running through
    graceful termination — exactly like a preemption victim. Its chips
    must stay masked from every placement until the eviction executor
    confirms the pod object gone (victim_gone), then free."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_RESERVATION_TTL_SECONDS": "0.2",
    })
    with SimCluster(cfg) as c:
        import time
        group = PodGroup("doomed", min_member=4)
        _, alloc = c.schedule(c.make_pod("d-0", tpu=1, group=group))
        member_coord = TopologyCoord.of(alloc.coords[0])
        sid = c.extender.state.slice_of_node(alloc.node_name)
        time.sleep(0.3)
        gang = c.extender.gang
        assert ("default", "doomed") in gang.sweep()
        # ledger shows the chip free, but the mask holds it
        assert c.extender.state.allocation("default/d-0") is None
        assert member_coord in gang.reserved_coords(sid)
        assert gang.terminating_count() == 1
        # a bystander wanting the whole node is infeasible while the
        # rolled-back member terminates (3 free + 1 masked of 4)...
        pod4 = c.make_pod("greedy", tpu=4)
        fres = c.extender.handle("filter", {
            "Pod": pod4, "Nodes": {"Items": c.node_objects()}})
        assert fres["NodeNames"] == []
        assert "gang reservations excluded" in str(fres["FailedNodes"])
        # ...the executor confirms the eviction; the chip frees for real
        assert c.drain_evictions() == ["default/d-0"]
        assert gang.terminating_count() == 0
        c.schedule(pod4)
        assert c.utilization() == 1.0


def test_rollback_queues_member_evictions():
    with SimCluster(_cfg(ttl="0.2")) as c:
        import time
        group = PodGroup("evict", min_member=8)
        for i in range(2):
            c.schedule(c.make_pod(f"v-{i}", tpu=1, group=group))
        time.sleep(0.3)
        c.extender.gang.sweep()
        evicted = c.drain_evictions()
        assert sorted(evicted) == ["default/v-0", "default/v-1"]
        assert "default/v-0" not in c.pods  # pod object gone, not just ledger


def test_two_gangs_dont_overlap():
    with SimCluster(_cfg()) as c:
        g1 = PodGroup("left", min_member=8)
        g2 = PodGroup("right", min_member=8)
        a1 = [c.schedule(c.make_pod(f"l-{i}", tpu=1, group=g1))[1] for i in range(8)]
        a2 = [c.schedule(c.make_pod(f"r-{i}", tpu=1, group=g2))[1] for i in range(8)]
        s1 = {tuple(co) for a in a1 for co in a.coords}
        s2 = {tuple(co) for a in a2 for co in a.coords}
        assert not (s1 & s2)
        assert c.utilization() == 1.0


def test_gang_link_fault_in_reserved_slice_rolls_back():
    """SURVEY.md §6: a dropped ICI link inside an uncommitted gang's slice
    rolls the gang back; re-reservation lands clear of the dead link."""
    with SimCluster(_cfg()) as c:
        group = PodGroup("linky", min_member=4)
        c.schedule(c.make_pod("l-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "linky")
        # drop a link between two chips INSIDE the reserved slice
        coords = sorted(res.coords)
        a = coords[0]
        b = next(nb for nb in c.mesh.neighbors(a) if nb in res.coords)
        c.inject_link_fault(a, b)
        c.schedule(c.make_pod("l-1", tpu=1, group=group))
        assert c.extender.gang.rollbacks == 1
        res2 = c.extender.gang.reservation("default", "linky")
        cs = res2.coords
        assert not (a in cs and b in cs)
        # rolled-back member rescheduled; gang completes on the new slice
        assert c.extender.state.allocation("default/l-0") is None
        c.schedule(c.make_pod("l-0b", tpu=1, group=group))
        for i in range(2, 4):
            c.schedule(c.make_pod(f"l-{i}", tpu=1, group=group))
        assert res2.committed


def test_gang_reservation_avoids_preexisting_link_fault():
    with SimCluster(_cfg()) as c:
        # partition awareness: link down in the middle of the mesh
        c.inject_link_fault((1, 1, 0), (2, 1, 0))
        group = PodGroup("careful", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"c-{i}", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "careful")
        cs = res.coords
        assert not (TopologyCoord(1, 1, 0) in cs and TopologyCoord(2, 1, 0) in cs)
        assert res.committed


def test_link_fault_restore_reopens_placement():
    with SimCluster(_cfg()) as c:
        # every x-link at the x=1|x=2 boundary down: no 16-chip slice
        for y in range(4):
            c.inject_link_fault((1, y, 0), (2, y, 0))
        group = PodGroup("whole", min_member=16)
        with pytest.raises(RuntimeError, match="no contiguous slice"):
            c.schedule(c.make_pod("w-0", tpu=1, group=group))
        for y in range(4):
            c.inject_link_fault((1, y, 0), (2, y, 0), up=True)
        for i in range(16):
            c.schedule(c.make_pod(f"w-{i}", tpu=1, group=group))
        assert c.extender.gang.reservation("default", "whole").committed

import pytest

from tpukube.core.config import TpuKubeConfig, load_config


def test_defaults():
    cfg = load_config(env={})
    assert cfg.resource_tpu == "qiniu.com/tpu"
    assert cfg.shares_per_chip == 1
    assert cfg.sim_mesh().num_chips == 64
    assert cfg.plugin_socket_path().endswith("device-plugins/tpukube.sock")


def test_yaml_then_env_precedence(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("shares_per_chip: 2\nextender_port: 9999\nsim_mesh_dims: [8, 8, 1]\n")
    cfg = load_config(str(p), env={"TPUKUBE_EXTENDER_PORT": "7777"})
    assert cfg.shares_per_chip == 2
    assert cfg.extender_port == 7777  # env wins over yaml
    assert cfg.sim_mesh_dims == (8, 8, 1)


def test_env_tuple_parsing():
    cfg = load_config(env={"TPUKUBE_SIM_MESH_DIMS": "4x4x2", "TPUKUBE_SIM_TORUS": "true,false,false"})
    assert cfg.sim_mesh_dims == (4, 4, 2)
    assert cfg.sim_torus == (True, False, False)


def test_rejects_unknown_yaml_key(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("not_a_key: 1\n")
    with pytest.raises(ValueError):
        load_config(str(p), env={})


def test_rejects_bad_values():
    with pytest.raises(ValueError):
        load_config(env={"TPUKUBE_SHARES_PER_CHIP": "0"})
    with pytest.raises(ValueError):
        load_config(env={"TPUKUBE_SCORE_MODE": "chaos"})
    with pytest.raises(ValueError):
        load_config(env={"TPUKUBE_BACKEND": "cuda"})


def test_config_is_frozen():
    cfg = TpuKubeConfig()
    with pytest.raises(Exception):
        cfg.shares_per_chip = 5  # type: ignore[misc]

"""Incremental snapshot maintenance (ISSUE 10): the O(Δ) delta-advance
path must be indistinguishable from a cold ledger rebuild at EVERY
epoch — property-tested over random mutation sequences covering every
seam (commit / release / upsert / reserve / bind / member-release /
rollback / dissolve / terminating / victim-gone), including the
overflow→full-rebuild fallback, the structural-change markers, and the
unchanged-payload no-bump case — and the whole webhook stack must place
bit-identically with the feature on vs the rebuild-every-epoch oracle.
"""

from __future__ import annotations

import random

import pytest

from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    RESOURCE_TPU,
    AllocResult,
    ChipInfo,
    ContainerInfo,
    Health,
    NodeInfo,
    PodGroup,
    PodInfo,
    ResourceList,
    TopologyCoord,
    make_device_id,
)
from tpukube.sched.extender import Extender
from tpukube.sched.snapshot import SnapshotDelta, _audit_divergence
from tpukube.sim.harness import SimCluster


def _mini_extender(dims=(4, 4, 2), host_block=(2, 2, 1), env=None):
    cfg = load_config(env=env or {})
    mesh = MeshSpec(dims=dims, host_block=host_block)
    ext = Extender(cfg)
    for host in mesh.all_hosts():
        chips = [
            ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        ext.state.upsert_node(host, codec.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id),
            mesh))
    return ext, mesh, cfg


def _pod(name, tpu=1, priority=0, group=None):
    return PodInfo(name=name, priority=priority, group=group, containers=[
        ContainerInfo(name="main",
                      requests=ResourceList({RESOURCE_TPU: tpu})),
    ])


def _assert_fresh(ext, context=""):
    """The delta-advanced snapshot equals a cold ledger rebuild."""
    snap = ext.snapshots.current()
    fresh = ext.snapshots._build(snap.key)
    diffs = _audit_divergence(snap, fresh)
    assert diffs == [], f"{context}: delta-advanced snapshot diverged: "\
                        f"{diffs}"


# -- the property test: random mutation replay -------------------------------

class _Driver:
    """Random-walk mutation driver over the real state/gang seams. The
    test tracks just enough bookkeeping to keep every op legal; the
    snapshot comparison after each op is the property."""

    def __init__(self, ext, mesh, cfg, rng):
        self.ext, self.mesh, self.cfg, self.rng = ext, mesh, cfg, rng
        self.sid = cfg.slice_id
        self.live: dict[str, AllocResult] = {}
        self.gang_n = 0
        self.pod_n = 0
        self.terminating: list[str] = []

    def _free_chip(self):
        occupied = self.ext.state.occupied_coords(self.sid)
        reserved = self.ext.gang.reserved_coords(self.sid)
        hosts = self.ext.state.hosts_by_coord(self.sid)
        free = [c for c in hosts if c not in occupied and
                c not in reserved]
        return self.rng.choice(sorted(free)) if free else None

    def op_commit(self):
        coord = self._free_chip()
        if coord is None:
            return
        node = self.ext.state.hosts_by_coord(self.sid)[coord]
        view = self.ext.state.node(node)
        self.pod_n += 1
        key = f"default/p-{self.pod_n}"
        alloc = AllocResult(
            pod_key=key, node_name=node,
            device_ids=[make_device_id(view.index_at(coord))],
            coords=[coord],
        )
        self.ext.state.commit(alloc)
        self.live[key] = alloc

    def op_release(self):
        if not self.live:
            return
        key = self.rng.choice(sorted(self.live))
        self.live.pop(key)
        self.ext.state.release(key)
        self.ext.gang.on_release(key)

    def op_gang_cycle(self):
        """reserve -> bind one member -> maybe rollback-by-TTL or
        dissolve (each path exercises distinct seams)."""
        self.gang_n += 1
        group = PodGroup(f"g{self.gang_n}", min_member=2)
        pod = _pod(f"g{self.gang_n}-0", group=group)
        try:
            res = self.ext.gang.ensure_reservation(pod, 1)
        except Exception:
            return  # mesh too full for a 2-chip box right now
        _assert_fresh(self.ext, "after reserve")
        roll = self.rng.random()
        if roll < 0.4:
            # bind one member, then leave the gang to TTL out later
            coords = sorted(res.unassigned_in(self.sid))[:1]
            if coords:
                node = self.ext.state.hosts_by_coord(self.sid)[coords[0]]
                view = self.ext.state.node(node)
                key = f"default/g{self.gang_n}-0"
                self.ext.state.commit(AllocResult(
                    pod_key=key, node_name=node,
                    device_ids=[make_device_id(
                        view.index_at(coords[0]))],
                    coords=list(coords),
                ))
                self.ext.gang.on_bound(res, key, list(coords), node)
                _assert_fresh(self.ext, "after on_bound")
                self.ext.gang.on_release(key)
                self.ext.state.release(key)
                _assert_fresh(self.ext, "after member release")
            self.ext.gang.sweep(now=1e9)  # TTL rollback
        elif roll < 0.7:
            self.ext.gang.dissolve(res.key)
        else:
            self.ext.gang.sweep(now=1e9)

    def op_terminating(self):
        coord = self._free_chip()
        if coord is None:
            return
        self.gang_n += 1
        group = PodGroup(f"t{self.gang_n}", min_member=2)
        try:
            res = self.ext.gang.ensure_reservation(
                _pod(f"t{self.gang_n}-0", group=group), 1)
        except Exception:
            return
        victim = f"default/v-{self.gang_n}"
        self.ext.gang.register_terminating(
            res, {victim: (self.sid, [coord])})
        self.terminating.append(victim)
        _assert_fresh(self.ext, "after register_terminating")
        if self.rng.random() < 0.7:
            self.ext.gang.on_victim_gone(victim)
            self.terminating.remove(victim)
        self.ext.gang.dissolve(res.key)

    def _reannotate(self, host, flip_health=False, bad_links=None):
        view = self.ext.state.node(host)
        chips = []
        for i, c in enumerate(self.mesh.coords_of_host(host)):
            chip = ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                            hbm_bytes=self.cfg.hbm_bytes_per_chip,
                            health=view.chip(i).health)
            if flip_health and i == 0:
                chip.health = (
                    Health.UNHEALTHY
                    if view.chip(0).health is Health.HEALTHY
                    else Health.HEALTHY
                )
            chips.append(chip)
        links = (view.info.bad_links if bad_links is None
                 else bad_links)
        self.ext.state.upsert_node(host, codec.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=self.sid,
                     bad_links=list(links)),
            self.mesh))

    def op_upsert_health_flip(self):
        """A HEALTH-ONLY re-annotation travels as an O(chips-per-node)
        delta (ISSUE 11 satellite): no full rebuild, and the advanced
        snapshot still matches the oracle (checked by _assert_fresh
        after every step — unhealthy/occupied sets AND utilization)."""
        host = self.rng.choice(sorted(self.ext.state.node_names()))
        r0 = self.ext.snapshots.rebuilds
        a0 = self.ext.snapshots.delta_applies
        self._reannotate(host, flip_health=True)
        self.ext.snapshots.current()
        assert self.ext.snapshots.rebuilds == r0, \
            "a health-only re-annotation must advance as a delta, " \
            "not force a full rebuild"
        assert self.ext.snapshots.delta_applies == a0 + 1

    def op_upsert_link_flip(self):
        """A LINK change stays a structural marker: the next lookup
        must full-rebuild, and still match the oracle."""
        host = self.rng.choice(sorted(self.ext.state.node_names()))
        view = self.ext.state.node(host)
        coords = self.mesh.coords_of_host(host)
        link = None
        for c in coords:
            for nb in self.mesh.neighbors(c):
                link = (min(c, nb), max(c, nb))
                break
            if link is not None:
                break
        if link is None:
            return
        r0 = self.ext.snapshots.rebuilds
        have = set(view.info.bad_links)
        bad = sorted(have - {link}) if link in have else \
            sorted(have | {link})
        self._reannotate(host, bad_links=bad)
        self.ext.snapshots.current()
        assert self.ext.snapshots.rebuilds == r0 + 1, \
            "a link-fault re-annotation must force a full rebuild"

    def op_upsert_unchanged(self):
        """Identical payload: no bump, no delta, cache stays hot."""
        host = self.rng.choice(sorted(self.ext.state.node_names()))
        view = self.ext.state.node(host)
        annos = {codec.ANNO_NODE_TOPOLOGY: view.raw_payload}
        before = self.ext.state.epoch()
        log_before = len(self.ext.snapshots._delta_log["ledger"])
        snap = self.ext.snapshots.current()
        self.ext.state.upsert_node(host, annos)
        assert self.ext.state.epoch() == before
        assert len(self.ext.snapshots._delta_log["ledger"]) == log_before
        assert self.ext.snapshots.current() is snap

    def step(self):
        op = self.rng.choice([
            self.op_commit, self.op_commit, self.op_commit,
            self.op_release, self.op_release,
            self.op_gang_cycle,
            self.op_terminating,
            self.op_upsert_health_flip,
            self.op_upsert_link_flip,
            self.op_upsert_unchanged,
        ])
        op()
        _assert_fresh(self.ext, f"after {op.__name__}")


@pytest.mark.parametrize("seed", [7, 23, 1031])
def test_property_random_mutations_delta_equals_cold_rebuild(seed):
    ext, mesh, cfg = _mini_extender()
    driver = _Driver(ext, mesh, cfg, random.Random(seed))
    for _ in range(120):
        driver.step()
    # the delta path actually carried the run (not rebuild-everything)
    assert ext.snapshots.delta_applies > ext.snapshots.rebuilds


def test_overflow_falls_back_to_full_rebuild(monkeypatch):
    """More bumps than the log bound between two lookups: the advance
    must detect the gap, count an overflow, rebuild — and be right."""
    from collections import deque

    ext, mesh, cfg = _mini_extender()
    snap0 = ext.snapshots.current()
    # shrink the live log so a short run overflows it
    with ext.snapshots._lock:
        for kind in ("ledger", "gang"):
            ext.snapshots._delta_log[kind] = deque(
                ext.snapshots._delta_log[kind], maxlen=4)
    hosts = sorted(ext.state.hosts_by_coord(cfg.slice_id).items())
    for n, (coord, node) in enumerate(hosts[:8]):
        view = ext.state.node(node)
        ext.state.commit(AllocResult(
            pod_key=f"default/of-{n}", node_name=node,
            device_ids=[make_device_id(view.index_at(coord))],
            coords=[coord],
        ))
    r0, o0 = ext.snapshots.rebuilds, ext.snapshots.delta_overflows
    _assert_fresh(ext, "after overflow")
    assert ext.snapshots.delta_overflows == o0 + 1
    assert ext.snapshots.rebuilds == r0 + 1
    assert ext.snapshots.current() is not snap0


def test_missing_note_degrades_to_rebuild_never_stale():
    """A bump whose seam forgot to note() shows up as a log gap: the
    advance refuses the chain and rebuilds — stale is impossible."""
    ext, mesh, cfg = _mini_extender()
    ext.snapshots.current()
    # simulate a rogue seam: bump without a note
    with ext.state._lock:
        ext.state._epoch += 1
    r0 = ext.snapshots.rebuilds
    _assert_fresh(ext, "after unnoted bump")
    assert ext.snapshots.rebuilds == r0 + 1


def test_delta_disabled_is_the_rebuild_oracle():
    ext, mesh, cfg = _mini_extender(
        env={"TPUKUBE_SNAPSHOT_DELTA_ENABLED": "0"})
    assert ext.snapshots.delta_enabled is False
    ext.snapshots.current()
    r0 = ext.snapshots.rebuilds
    node = sorted(ext.state.node_names())[0]
    view = ext.state.node(node)
    ext.state.commit(AllocResult(
        pod_key="default/a", node_name=node,
        device_ids=[make_device_id(0)],
        coords=[view.chip(0).coord],
    ))
    _assert_fresh(ext, "delta off")
    assert ext.snapshots.rebuilds == r0 + 1
    assert ext.snapshots.delta_applies == 0
    assert not ext.snapshots._delta_log["ledger"]  # note() is a no-op


def test_audit_sentinel_catches_a_wrong_delta():
    """The runtime cross-check on the delta math: a delta that
    mis-states its seam's effect serves a diverged snapshot, and the
    audit (rate 1.0) must raise on the next scheduling hit."""
    from tpukube.sched.snapshot import SnapshotAuditError

    ext, mesh, cfg = _mini_extender()
    ext.snapshots.audit_rate = 1.0
    ext.snapshots.current()
    node = sorted(ext.state.node_names())[0]
    view = ext.state.node(node)
    # a commit whose recorded delta LIES about the chip it occupied
    with ext.state._lock:
        view.add_ids([make_device_id(0)])
        ext.state._allocs["default/liar"] = AllocResult(
            pod_key="default/liar", node_name=node,
            device_ids=[make_device_id(0)],
            coords=[view.chip(0).coord],
        )
        ext.state._epoch += 1
        ext.state._delta_sink.note(SnapshotDelta(
            kind="ledger", epoch=ext.state._epoch,
            slice_id=cfg.slice_id,
            occupied_add=(view.chip(1).coord,),  # WRONG chip
            used_shares_delta=1,
        ))
    ext.snapshots.current()  # applies the lying delta
    with pytest.raises(SnapshotAuditError):
        ext.snapshots.current()  # audited hit: rebuild-and-compare


def test_utilization_advances_with_deltas():
    ext, mesh, cfg = _mini_extender()
    sid = cfg.slice_id
    ext.snapshots.current()
    node = sorted(ext.state.node_names())[0]
    view = ext.state.node(node)
    ext.state.commit(AllocResult(
        pod_key="default/u", node_name=node,
        device_ids=[make_device_id(i) for i in range(4)],
        coords=[c.coord for c in view.info.chips],
    ))
    ss = ext.snapshots.current().slice(sid)
    assert ss.utilization == ext.state.slice_utilization(sid)
    ext.state.release("default/u")
    ss = ext.snapshots.current().slice(sid)
    assert ss.utilization == ext.state.slice_utilization(sid) == 0.0


def test_untouched_slices_share_objects_touched_invalidate():
    """Only touched slices get fresh SliceSnapshots (lazy sweeps of
    untouched slices stay warm across the advance)."""
    cfg = load_config(env={})
    slices = {
        "s0": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
        "s1": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
    }
    with SimCluster(cfg, slices=slices, in_process=True) as c:
        ext = c.extender
        # warm: the first webhook ingests both slices' node annotations
        c.schedule(c.make_pod("warm", tpu=1))
        snap0 = ext.snapshots.current()
        assert len(snap0.slices) == 2
        # place one pod; only its slice's snapshot object may change
        _, alloc = c.schedule(c.make_pod("one", tpu=1))
        sid = ext.state.slice_of_node(alloc.node_name)
        other = next(s for s in snap0.slices if s != sid)
        snap1 = ext.snapshots.current()
        assert snap1.slices[sid] is not snap0.slices[sid]
        assert snap1.slices[other] is snap0.slices[other]


# -- webhook-stack parity: delta-advanced vs rebuild-every-epoch oracle ------

def _run_mixed_workload(delta: bool):
    """The placement-relevant decision log of a workload exercising
    singles, a multi-chip pod, churn, a gang, and a preemption — with
    the delta path on vs the rebuild-every-epoch oracle."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SNAPSHOT_DELTA_ENABLED": "1" if delta else "0",
        "TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0",
    })
    out = {}
    with SimCluster(cfg, in_process=True) as c:
        for i in range(6):
            _, alloc = c.schedule(c.make_pod(f"s-{i}", tpu=1))
            out[f"s-{i}"] = (alloc.node_name, tuple(alloc.device_ids))
        _, alloc = c.schedule(c.make_pod("wide", tpu=4))
        out["wide"] = (alloc.node_name, tuple(alloc.device_ids))
        c.complete_pod("s-2")
        _, alloc = c.schedule(c.make_pod("refill", tpu=1))
        out["refill"] = (alloc.node_name, tuple(alloc.device_ids))
        fill = 0
        while True:
            try:
                _, alloc = c.schedule(c.make_pod(f"f-{fill}", tpu=1))
                out[f"f-{fill}"] = (alloc.node_name,
                                    tuple(alloc.device_ids))
                fill += 1
            except RuntimeError:
                break
        group = PodGroup("boss", min_member=8)
        for i in range(8):
            _, alloc = c.schedule(
                c.make_pod(f"b-{i}", tpu=1, priority=100, group=group))
            out[f"b-{i}"] = (alloc.node_name, tuple(alloc.device_ids))
        out["__preempt"] = c.extender.preemptions
        out["__audit_divergences"] = \
            c.extender.snapshots.audit_divergences
        out["__delta_applies_positive"] = \
            c.extender.snapshots.delta_applies > 0
    return out


def test_webhook_placement_parity_delta_vs_rebuild_oracle():
    oracle = _run_mixed_workload(delta=False)
    live = _run_mixed_workload(delta=True)
    assert live["__delta_applies_positive"]
    assert live["__audit_divergences"] == 0
    # placements bit-identical; normalize the differing meta keys
    for d in (oracle, live):
        d.pop("__delta_applies_positive")
    assert oracle == live


def test_delta_metrics_and_statusz_render_only_when_enabled():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    on, _, _ = _mini_extender()
    on.snapshots.current()
    text = render_extender_metrics(on)
    assert "# TYPE tpukube_snapshot_delta_applies_total counter" in text
    assert "tpukube_snapshot_delta_overflows_total 0" in text
    assert "tpukube_snapshot_delta_apply_seconds" in text
    doc = extender_statusz(on)["snapshot"]
    assert doc["delta"]["enabled"] is True
    assert "delta_hit_rate" in doc

    off, _, _ = _mini_extender(
        env={"TPUKUBE_SNAPSHOT_DELTA_ENABLED": "0"})
    off.snapshots.current()
    text = render_extender_metrics(off)
    # legacy exposition byte-identical with the feature off: none of
    # the delta series render
    assert "tpukube_snapshot_delta" not in text
    assert extender_statusz(off)["snapshot"]["delta"]["enabled"] is False


def test_kilonode10k_scenario_smoke(monkeypatch):
    """Scenario 12 at a tier-1-friendly scale: the full 10240-node /
    40960-chip control plane, ~2.5k pods on the fake clock. The real
    12k/40k-pod runs live in tools/check.sh and bench.py; this asserts
    the machinery end to end — batched gang planning placed the
    512-member gang, the delta path carried snapshot maintenance, zero
    divergence, zero leaks (the scenario raises on either)."""
    from tpukube.sim import scenarios

    monkeypatch.setenv("TPUKUBE_KILONODE10K_PODS", "2500")
    monkeypatch.delenv("TPUKUBE_BATCH_ENABLED", raising=False)
    r = scenarios.run(12)
    assert r["nodes"] == 10240 and r["chips"] == 40960
    assert r["pods_total"] == 2500
    assert r["gang_committed"]
    assert r["ledger_divergence"] == 0
    assert r["cycle"]["gang_batches"] >= 1
    assert r["cycle"]["gang_batch_members"] == 512
    assert r["cycle"]["plan_hit_ratio"] > 0.9
    assert r["snapshot"]["delta_applies"] > 0
    assert r["snapshot"]["rebuild_p50_ms"] > 0


def test_largest_free_box_bisection_matches_exhaustive_scan():
    """ISSUE 10 slicefit touch: largest_free_box_in bisects the third
    extent (feasibility is monotone per axis) — the result must equal
    the exhaustive all-tiers scan on arbitrary grids, torus included."""
    import numpy as np

    from tpukube.sched import slicefit
    from tpukube.sched.slicefit import _Sweep

    def exhaustive(sweep):
        best = 0
        X, Y, Z = sweep.mesh.dims
        for a in range(1, X + 1):
            for b in range(1, Y + 1):
                for c in range(1, Z + 1):
                    if a * b * c > best and len(
                            sweep.origins((a, b, c))):
                        best = a * b * c
        return best

    rng = random.Random(42)
    for _ in range(40):
        dims = (rng.randint(1, 6), rng.randint(1, 6), rng.randint(1, 6))
        torus = (rng.random() < 0.3, rng.random() < 0.3,
                 rng.random() < 0.3)
        mesh = MeshSpec(dims=dims, host_block=(1, 1, 1), torus=torus)
        grid = np.zeros(dims, dtype=bool)
        for _ in range(rng.randint(0, mesh.num_chips)):
            grid[rng.randrange(dims[0]), rng.randrange(dims[1]),
                 rng.randrange(dims[2])] = True
        got = slicefit.largest_free_box_in(_Sweep(mesh, grid))
        want = exhaustive(_Sweep(mesh, grid))
        assert got == want, (dims, torus, got, want)

"""Tests for the C++ libtpuinfo layer through the ctypes wrapper.

The native selftest binary (incl. the ASan/UBSan build) covers the C side;
these tests cover the Python marshalling and the sim backend semantics the
node agent depends on.
"""

import subprocess

import pytest

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import Health, TopologyCoord
from tpukube.native import TpuInfo, TpuInfoError, sim_spec

MESH = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))


def _open(host="host-0-0-0", hbm=16 << 30, cores=1, mesh=MESH):
    return TpuInfo("sim", sim_spec(mesh, host, hbm, cores))


def test_sim_enumeration_matches_python_mesh():
    with _open(host="host-1-0-2") as ti:
        mesh = ti.mesh()
        assert mesh == MESH
        chips = ti.chips()
        assert len(chips) == MESH.chips_per_host == 4
        # C++ minting order must match MeshSpec.coords_of_host exactly:
        # the plugin's device ids depend on this agreement.
        assert [c.coord for c in chips] == MESH.coords_of_host("host-1-0-2")
        assert all(c.hbm_bytes == 16 << 30 for c in chips)
        assert all(c.health is Health.HEALTHY for c in chips)
        assert chips[0].chip_id == "host-1-0-2-chip-0"


def test_links_match_python_neighbors():
    with _open(host="host-0-0-0") as ti:
        for chip in ti.chips():
            got = set(ti.links(chip.index))
            want = set(MESH.neighbors(chip.coord))
            assert got == want, f"chip {chip.index} at {chip.coord}"


def test_links_torus_wrap():
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1), torus=(True, True, False))
    with _open(mesh=mesh) as ti:
        got = set(ti.links(0))  # chip at (0, 0, 0)
        assert TopologyCoord(3, 0, 0) in got and TopologyCoord(0, 3, 0) in got
        assert got == set(mesh.neighbors(TopologyCoord(0, 0, 0)))


def test_fault_injection_roundtrip():
    with _open() as ti:
        ti.inject_fault(2)
        assert ti.chips()[2].health is Health.UNHEALTHY
        assert ti.chips()[0].health is Health.HEALTHY
        ti.inject_fault(2, healthy=True)
        assert ti.chips()[2].health is Health.HEALTHY
        with pytest.raises(TpuInfoError, match="out of range"):
            ti.inject_fault(99)


def test_bad_specs_raise():
    with pytest.raises(TpuInfoError, match="host_block"):
        TpuInfo("sim", "dims=4,4,4\nhost_block=3,3,3")
    with pytest.raises(TpuInfoError, match="unknown backend"):
        TpuInfo("cuda")
    with pytest.raises(TpuInfoError, match="host outside"):
        TpuInfo("sim", sim_spec(MESH, "host-9-0-0", 1 << 30))


def test_double_init_and_close_semantics():
    ti = _open()
    with pytest.raises(TpuInfoError, match="already initialized"):
        TpuInfo("sim", sim_spec(MESH, "host-0-0-0", 1 << 30))
    ti.close()
    ti.close()  # idempotent
    with pytest.raises(TpuInfoError, match="closed"):
        ti.chips()
    # after close, a fresh session works
    with _open() as ti2:
        assert ti2.chip_count() == 4


def test_real_backend_bogus_libtpu_fails_cleanly():
    with pytest.raises(TpuInfoError, match="cannot load libtpu"):
        TpuInfo("real", "libtpu=/nonexistent/libtpu.so")


def test_native_selftest_binary_passes():
    import os

    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tpukube",
        "native",
    )
    proc = subprocess.run(
        ["make", "-C", native_dir, "selftest"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all checks passed" in proc.stdout


def test_native_selftest_under_asan_ubsan():
    """SURVEY.md §6 race/sanitizer story: the C++ enumeration layer must be
    clean under AddressSanitizer + UBSan (hbmguard interposes malloc and is
    exercised sanitizer-free by `make selftest` instead — the two allocator
    layers cannot coexist in one process)."""
    import os

    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tpukube",
        "native",
    )
    proc = subprocess.run(
        ["make", "-C", native_dir, "asan"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all checks passed" in proc.stdout
    assert "runtime error" not in proc.stderr  # UBSan reports go to stderr


def test_link_fault_injection_roundtrip():
    with _open() as ti:
        assert ti.link_faults() == []
        a, b = TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)
        ti.inject_link_fault(b, a)  # reversed order canonicalizes
        assert ti.link_faults() == [(a, b)]
        ti.inject_link_fault(a, b)  # idempotent re-drop
        assert ti.link_faults() == [(a, b)]
        ti.inject_link_fault(a, b, up=True)
        assert ti.link_faults() == []


def test_link_fault_rejects_non_adjacent():
    with _open() as ti:
        with pytest.raises(TpuInfoError, match="adjacent"):
            ti.inject_link_fault(TopologyCoord(0, 0, 0), TopologyCoord(2, 0, 0))
        with pytest.raises(TpuInfoError, match="adjacent"):
            ti.inject_link_fault(TopologyCoord(0, 0, 0), TopologyCoord(1, 1, 0))
        # no torus on this mesh: the wrap pair is not adjacent
        with pytest.raises(TpuInfoError, match="adjacent"):
            ti.inject_link_fault(TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))


def test_link_fault_torus_wrap_adjacency():
    mesh = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1),
                    torus=(True, False, False))
    with _open(mesh=mesh) as ti:
        ti.inject_link_fault(TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))
        assert ti.link_faults() == [
            (TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))
        ]

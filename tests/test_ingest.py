"""ISSUE 15: bulk cold-start ingestion + generation-based incremental
resync.

The acceptance gates:
  * bulk ``upsert_nodes`` produces ledger/snapshot/cache state
    identical to per-node upserts (mixed health/link/vTPU payloads,
    error items, changed-payload re-annotations), with the audit
    sentinel re-deriving via full walks so the probe-seeded caches can
    never hide a missed seam;
  * a mid-ingest crash recovers through the scenario-13 journal
    machinery (the "nodes" WAL record replays through the same fast
    path; a lost record reconciles from the apiserver);
  * ``allocs_since`` equals the full-read diff at every step of a
    random lifecycle, and a gap/overflow/restart ALWAYS degrades to a
    full read — never a stale answer;
  * the lifecycle resync and the router's federated ``allocations``
    path move O(changed-allocs) wire bytes per churn wave;
  * a killed replica's warm restart replays its own journal segment
    (ROADMAP sharding item (d)) with the cold re-ingest as the
    failure ladder.
"""

from __future__ import annotations

import json
import random

import pytest

from tpukube.chaos import ledger_divergence
from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    AllocResult,
    ChipInfo,
    Health,
    NodeInfo,
    PodGroup,
    TopologyCoord,
    make_device_id,
)
from tpukube.sched.extender import Extender
from tpukube.sched.snapshot import _audit_divergence
from tpukube.sim.harness import SimCluster

MESH = MeshSpec(dims=(4, 4, 2), host_block=(2, 2, 1))


def _fleet_items(mesh=MESH, sid="slice-0", vtpu_hosts=(),
                 unhealthy_hosts=(), link_hosts=(), prefix=""):
    """Node-annotation items over one slice, with optional per-host
    health flips, bad ICI links, and vTPU share payloads — the mixed
    shapes the parity suite must not collapse."""
    items = []
    for host in mesh.all_hosts():
        name = prefix + host
        coords = mesh.coords_of_host(host)
        chips = [
            ChipInfo(chip_id=f"{name}-c{i}", index=i, coord=c,
                     hbm_bytes=16 * 2 ** 30)
            for i, c in enumerate(coords)
        ]
        if host in unhealthy_hosts:
            chips[0].health = Health.UNHEALTHY
        info = NodeInfo(
            name=name, chips=chips, slice_id=sid,
            shares_per_chip=4 if host in vtpu_hosts else 1,
        )
        if host in link_hosts:
            for other in coords[1:]:
                if other in mesh.neighbors(coords[0]):
                    info.bad_links = [(coords[0], other)]
                    break
        items.append({"name": name,
                      "annotations": codec.annotate_node(info, mesh)})
    return items


def _mixed_items():
    hosts = MESH.all_hosts()
    return _fleet_items(vtpu_hosts={hosts[1]},
                        unhealthy_hosts={hosts[2]},
                        link_hosts={hosts[3]})


def _ingest(items, bulk: bool, cfg=None) -> Extender:
    ext = Extender(cfg or load_config(env={}))
    ext.bulk_ingest = bulk
    results = ext.upsert_nodes_many(items)
    assert all(r == {"ours": True} for r in results), results
    return ext


def _fingerprint(ext: Extender) -> dict:
    """Everything observable about the ingested state, with the cached
    reads CROSS-CHECKED against their ground-truth walks (a probe-
    seeded cache that disagrees with the walk is the bug this suite
    exists to catch)."""
    st = ext.state
    while st.warm_pending(4096):
        pass
    out = {"names": st.node_names(), "slices": sorted(st.slice_ids()),
           "utilization": st.utilization()}
    for sid in st.slice_ids():
        occ, wocc = st.occupied_coords(sid), st.walk_occupied_coords(sid)
        unh, wunh = st.unhealthy_coords(sid), st.walk_unhealthy_coords(sid)
        brk, wbrk = st.broken_links(sid), st.walk_broken_links(sid)
        shr = st.slice_share_counts(sid)
        wshr = st.walk_slice_share_counts(sid)
        assert occ == wocc and unh == wunh and brk == wbrk \
            and tuple(shr) == tuple(wshr), f"cache != walk in {sid}"
        out[sid] = (frozenset(occ), frozenset(unh), frozenset(brk),
                    tuple(shr))
    out["nodes"] = {}
    for name in st.node_names():
        view = st.node(name)
        out["nodes"][name] = (
            view.raw_payload,
            view.shares_per_chip,
            tuple(sorted((c.index, tuple(c.coord), c.health.value)
                         for c in view.info.chips)),
        )
    return out


# -- parity: bulk ingest vs per-node upserts --------------------------------

def test_bulk_ingest_parity_mixed_payloads():
    items = _mixed_items()
    bulk = _ingest(items, bulk=True)
    per = _ingest(items, bulk=False)
    assert _fingerprint(bulk) == _fingerprint(per)
    # the scheduling snapshots agree too (content, not cache keys)
    diffs = _audit_divergence(bulk.snapshots.current(),
                              per.snapshots.current())
    assert diffs == [], diffs


def test_bulk_ingest_error_items_match_per_node():
    """Every malformed shape errors with the per-node path's message,
    and a bad item never poisons its batchmates."""
    good = _mixed_items()
    bad_json = {"name": "bj", "annotations": {
        codec.ANNO_NODE_TOPOLOGY: "{nope"}}
    wrong_name = json.loads(
        good[0]["annotations"][codec.ANNO_NODE_TOPOLOGY])
    wrong_name["node"] = "imposter"
    name_item = {"name": "real-name", "annotations": {
        codec.ANNO_NODE_TOPOLOGY: json.dumps(wrong_name)}}
    small = MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1))
    mesh_item = _fleet_items(mesh=small, prefix="m-")[0]
    conflict = json.loads(
        good[1]["annotations"][codec.ANNO_NODE_TOPOLOGY])
    conflict["node"] = "claim-jumper"
    conflict_item = {"name": "claim-jumper", "annotations": {
        codec.ANNO_NODE_TOPOLOGY: json.dumps(conflict)}}
    batch = good + [bad_json, name_item, mesh_item, conflict_item,
                    {"name": "no-anno", "annotations": {}}]

    bulk = Extender(load_config(env={}))
    res = bulk.upsert_nodes_many(batch)

    per = Extender(load_config(env={}))
    per.bulk_ingest = False
    res_per = per.upsert_nodes_many(batch)
    assert res == res_per
    assert all(r == {"ours": True} for r in res[:len(good)])
    assert "bad JSON" in res[len(good)]["error"]
    assert "imposter" in res[len(good) + 1]["error"]
    assert "must agree on its geometry" in res[len(good) + 2]["error"]
    assert "both claim" in res[len(good) + 3]["error"]
    assert res[-1] == {"ours": False}
    assert _fingerprint(bulk) == _fingerprint(per)


def test_bulk_ingest_batch_internal_conflict_unwinds_cleanly():
    """Two items of ONE batch claiming the same chips: first stages,
    second errors, and the survivor's claims are intact."""
    items = _mixed_items()
    dup = json.loads(items[0]["annotations"][codec.ANNO_NODE_TOPOLOGY])
    dup["node"] = "dup"
    batch = [items[0], {"name": "dup", "annotations": {
        codec.ANNO_NODE_TOPOLOGY: json.dumps(dup)}}] + items[1:]
    ext = Extender(load_config(env={}))
    res = ext.upsert_nodes_many(batch)
    assert res[0] == {"ours": True}
    assert "both claim" in res[1]["error"]
    assert all(r == {"ours": True} for r in res[2:])
    per = Extender(load_config(env={}))
    per.bulk_ingest = False
    per.upsert_nodes_many(batch)
    assert _fingerprint(ext) == _fingerprint(per)


def test_bulk_ingest_duplicate_name_in_one_batch_matches_per_node():
    """The SAME node listed twice in one batch (webhook bodies repeat
    candidates): identical payload answers True twice like the
    per-node path's unchanged-payload second upsert — never a
    both-claim error (the name-string identity staging trick must not
    compare cross-item) — and a CHANGED second payload lands the
    re-annotation path."""
    items = _mixed_items()
    batch = [items[0], dict(items[0])] + items[1:]
    bulk = Extender(load_config(env={}))
    res = bulk.upsert_nodes_many(batch)
    per = Extender(load_config(env={}))
    per.bulk_ingest = False
    assert res == per.upsert_nodes_many(batch)
    assert res[0] == res[1] == {"ours": True}
    assert _fingerprint(bulk) == _fingerprint(per)
    # duplicate with a CHANGED payload: second occurrence re-annotates
    doc = json.loads(items[0]["annotations"][codec.ANNO_NODE_TOPOLOGY])
    doc["chips"][0]["health"] = "Unhealthy"
    changed = {"name": items[0]["name"], "annotations": {
        codec.ANNO_NODE_TOPOLOGY: json.dumps(doc)}}
    batch2 = [items[0], changed] + items[1:]
    b2 = Extender(load_config(env={}))
    r2 = b2.upsert_nodes_many(batch2)
    p2 = Extender(load_config(env={}))
    p2.bulk_ingest = False
    assert r2 == p2.upsert_nodes_many(batch2)
    assert _fingerprint(b2) == _fingerprint(p2)


def test_decode_counters_track_resend_suppression():
    """Cold ingest = all misses (every payload names its own node);
    re-sending the identical fleet = all hits, no parse."""
    items = _mixed_items()
    ext = _ingest(items, bulk=True)
    s0 = ext.state.ingest_stats()
    assert s0["decode_cache_misses"] == len(items)
    assert s0["decode_cache_hit_rate"] == 0.0
    res = ext.upsert_nodes_many(items)  # the webhook re-send shape
    assert all(r == {"ours": True} for r in res)
    s1 = ext.state.ingest_stats()
    assert s1["decode_cache_hits"] == len(items)
    assert s1["decode_cache_misses"] == len(items)
    assert s1["decode_cache_hit_rate"] == 0.5


def test_bulk_ingest_changed_payload_takes_per_node_path():
    """A re-annotation of a known node (health flip) through the bulk
    surface lands the per-node path's health-only delta semantics —
    state identical to a per-node upsert doing the same."""
    items = _mixed_items()
    flipped = []
    for item in items:
        doc = json.loads(item["annotations"][codec.ANNO_NODE_TOPOLOGY])
        if item["name"].endswith(MESH.all_hosts()[0]):
            doc["chips"][1]["health"] = "Unhealthy"
        flipped.append({"name": item["name"], "annotations": {
            codec.ANNO_NODE_TOPOLOGY: json.dumps(doc)}})

    exts = []
    for bulk in (True, False):
        ext = _ingest(items, bulk=bulk)
        ext.bulk_ingest = bulk
        res = ext.upsert_nodes_many(flipped)
        assert all(r == {"ours": True} for r in res)
        exts.append(ext)
    assert _fingerprint(exts[0]) == _fingerprint(exts[1])
    sid = exts[0].state.slice_ids()[0]
    assert len(exts[0].state.unhealthy_coords(sid)) == 2  # old + new


def test_bulk_ingest_append_to_live_slice_advances_caches():
    """A second batch adding NEW nodes to an already-seeded slice must
    advance the incremental caches, not reseed them (allocs committed
    in between survive)."""
    big = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))
    items = _fleet_items(mesh=big)
    first, second = items[:4], items[4:]
    ext = _ingest(first, bulk=True)
    alloc = AllocResult(pod_key="default/p0", node_name=first[0]["name"],
                        device_ids=[make_device_id(0)],
                        coords=[big.coords_of_host(big.all_hosts()[0])[0]])
    ext.state.commit(alloc)
    res = ext.upsert_nodes_many(second)
    assert all(r == {"ours": True} for r in res)
    per = Extender(load_config(env={}))
    per.bulk_ingest = False
    per.upsert_nodes_many(first)
    per.state.commit(alloc)
    per.upsert_nodes_many(second)
    assert _fingerprint(ext) == _fingerprint(per)
    assert ext.state.allocation("default/p0") is not None


def test_bulk_ingest_placement_parity_through_webhooks():
    """The whole webhook stack places identically with bulk ingest on
    vs off (the per-node oracle), audit sentinel at 1.0."""
    placements = {}
    for bulk in ("1", "0"):
        cfg = load_config(env={
            "TPUKUBE_BULK_INGEST_ENABLED": bulk,
            "TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0",
        })
        with SimCluster(cfg) as c:
            got = {}
            grp = PodGroup("g", min_member=4)
            for i in range(4):
                node, alloc = c.schedule(
                    c.make_pod(f"g-{i}", tpu=1, group=grp))
                got[f"g-{i}"] = (node, tuple(alloc.device_ids))
            for i in range(3):
                node, alloc = c.schedule(c.make_pod(f"p-{i}", tpu=2))
                got[f"p-{i}"] = (node, tuple(alloc.device_ids))
            assert c.extender.snapshots.audit_divergences == 0
            assert c.extender.snapshots.audit_checks > 0
            placements[bulk] = got
    assert placements["1"] == placements["0"]


def test_bulk_ingest_checkpoint_roundtrip_keeps_lazy(tmp_path):
    """A checkpoint captured over a still-lazy bulk-ingested fleet
    rides the RAW annotations; recovery keeps them lazy and first
    touch decodes to the same views."""
    from tpukube.sched import journal as journal_mod
    from tpukube.sched.shard import _ListApi

    env = {"TPUKUBE_JOURNAL_ENABLED": "1",
           "TPUKUBE_JOURNAL_PATH": str(tmp_path / "wal.jsonl")}
    items = _mixed_items()
    ext = _ingest_no_warm(items, env)
    alloc = AllocResult(pod_key="default/p0", node_name=items[0]["name"],
                        device_ids=[make_device_id(0)],
                        coords=[MESH.coords_of_host(MESH.all_hosts()[0])[0]])
    ext.state.commit(alloc)
    ext.journal.write_checkpoint_sync(ext.checkpoint_doc())
    # the commit materialized its own node; everything else stays lazy
    assert ext.state.ingest_stats()["lazy_pending"] == len(items) - 1
    ext.journal.close()
    ext.state.retire()

    ext2 = Extender(load_config(env=env))
    journal_mod.recover_extender(ext2, _ListApi(
        [{"metadata": {"name": it["name"],
                       "annotations": it["annotations"]}}
         for it in items],
        [_pod_obj(alloc)],
    ))
    assert ext2.state.allocation("default/p0") is not None
    oracle = _ingest(items, bulk=True)
    oracle.state.commit(alloc)
    fp2 = _fingerprint(ext2)
    assert fp2["nodes"] == _fingerprint(oracle)["nodes"]
    assert fp2["utilization"] == pytest.approx(
        oracle.state.utilization())


def _ingest_no_warm(items, env):
    """Bulk-ingest without triggering the background warmer (tests
    that must observe the lazy store call state.ingest_nodes
    directly)."""
    ext = Extender(load_config(env=env))
    results = ext.state.ingest_nodes(items)
    assert all(r == {"ours": True} for r in results), results
    return ext


def _pod_obj(alloc: AllocResult) -> dict:
    ns, name = alloc.pod_key.split("/", 1)
    return {
        "metadata": {"name": name, "namespace": ns,
                     "annotations": {
                         codec.ANNO_ALLOC: codec.encode_alloc(alloc)}},
        "spec": {"nodeName": alloc.node_name},
        "status": {"phase": "Running"},
    }


def test_mid_ingest_crash_replays_or_reconciles(tmp_path):
    """Scenario-13 machinery around the bulk seam: (a) a drained
    'nodes' WAL record replays through the same fast path on
    recovery; (b) a crash that LOSES the queued record still
    converges via the apiserver reconcile."""
    from tpukube.sched import journal as journal_mod
    from tpukube.sched.shard import _ListApi

    items = _mixed_items()
    node_objs = [{"metadata": {"name": it["name"],
                               "annotations": it["annotations"]}}
                 for it in items]
    for drained in (True, False):
        env = {"TPUKUBE_JOURNAL_ENABLED": "1",
               "TPUKUBE_JOURNAL_PATH": str(
                   tmp_path / f"wal-{drained}.jsonl")}
        ext = _ingest_no_warm(items, env)
        if drained:
            ext.journal.close()  # flushes the queued 'nodes' record
        else:
            ext.journal.crash()  # queued records LOST mid-ingest
        ext.state.retire()

        ext2 = Extender(load_config(env=env))
        journal_mod.recover_extender(ext2, _ListApi(node_objs, []))
        assert _fingerprint(ext2)["nodes"] == \
            _fingerprint(_ingest(items, bulk=True))["nodes"]
        ext2.journal.close()
        ext2.state.retire()


# -- generation-based incremental resync ------------------------------------

def _mini_committed_extender():
    ext = _ingest(_fleet_items(), bulk=True)
    free = []  # (node_name, chip_index, coord)
    for item in _fleet_items():
        name = item["name"]
        for i, c in enumerate(MESH.coords_of_host(name)):
            free.append((name, i, c))
    return ext, free


def _apply_delta(mirror: dict, delta: dict) -> None:
    if "full" in delta:
        mirror.clear()
        mirror.update({a.pod_key: a for a in delta["full"]})
    else:
        for key in delta["removes"]:
            mirror.pop(key, None)
        for a in delta["adds"]:
            mirror[a.pod_key] = a


def test_allocs_since_equals_full_read_property():
    """Seeded random lifecycle: a mirror advanced by ``allocs_since``
    equals the full read at EVERY read point, at several read
    cadences and log capacities (including gap-forcing ones)."""
    rng = random.Random(15)
    for capacity, cadence in ((65536, 1), (65536, 7), (8, 3), (4, 9)):
        ext, free = _mini_committed_extender()
        ext.state.set_generation_log(capacity)
        live: dict[str, tuple] = {}  # key -> (node, idx, coord)
        mirror: dict[str, AllocResult] = {}
        cursor = None
        seq = 0
        fulls = 0
        for step in range(120):
            if free and (not live or rng.random() < 0.6):
                node, idx, coord = free.pop(
                    rng.randrange(len(free)))
                alloc = AllocResult(
                    pod_key=f"default/p{seq}", node_name=node,
                    device_ids=[make_device_id(idx)], coords=[coord])
                seq += 1
                ext.state.commit(alloc)
                live[alloc.pod_key] = (node, idx, coord)
            else:
                key = rng.choice(sorted(live))
                slot = live.pop(key)
                ext.state.release(key)
                free.append(slot)
            if step % cadence == 0:
                delta = ext.state.allocs_since(cursor)
                cursor = delta["cursor"]
                assert delta["bytes"] >= 0
                if "full" in delta:
                    fulls += 1
                _apply_delta(mirror, delta)
                truth = {a.pod_key: a for a in ext.state.allocations()}
                assert mirror == truth, (capacity, cadence, step)
        if capacity >= 120:
            assert fulls == 1  # only the bootstrap read


def test_allocs_since_gap_and_restart_degrade_to_full():
    ext, free = _mini_committed_extender()
    ext.state.set_generation_log(2)
    d0 = ext.state.allocs_since(None)
    assert "full" in d0
    for i in range(4):  # 4 > capacity 2: the log gapped
        node, idx, coord = free.pop()
        ext.state.commit(AllocResult(
            pod_key=f"default/g{i}", node_name=node,
            device_ids=[make_device_id(idx)], coords=[coord]))
    d1 = ext.state.allocs_since(d0["cursor"])
    assert "full" in d1 and len(d1["full"]) == 4
    # a cursor from ANOTHER ledger incarnation: full, never stale
    other = Extender(load_config(env={}))
    other.state.set_generation_log(16)
    d2 = other.state.allocs_since(d1["cursor"])
    assert "full" in d2
    # a nonsense/future cursor: full
    inc, _gen = ext.state.generation()
    assert "full" in ext.state.allocs_since((inc, 10 ** 9))
    assert "full" in ext.state.allocs_since("garbage")


def test_allocs_since_disabled_returns_none():
    ext, _ = _mini_committed_extender()
    ext.state.set_generation_log(0)
    assert ext.state.allocs_since(None) is None


def test_generation_rides_checkpoint(tmp_path):
    """Recovery resumes the generation numbering (never regresses),
    and the fresh incarnation token full-reads any pre-crash cursor."""
    from tpukube.sched import journal as journal_mod
    from tpukube.sched.shard import _ListApi

    env = {"TPUKUBE_JOURNAL_ENABLED": "1",
           "TPUKUBE_JOURNAL_PATH": str(tmp_path / "wal.jsonl")}
    items = _mixed_items()
    ext = _ingest(items, bulk=False, cfg=load_config(env=env))
    allocs = []
    # hosts[1]/[2] carry the vTPU/unhealthy payload flips: commit on
    # plain healthy hosts so the lifecycle itself can't error
    for i, host in enumerate(MESH.all_hosts()[4:7]):
        a = AllocResult(pod_key=f"default/p{i}", node_name=host,
                        device_ids=[make_device_id(0)],
                        coords=[MESH.coords_of_host(host)[0]])
        ext.state.commit(a)
        allocs.append(a)
    old_cursor = ext.state.allocs_since(None)["cursor"]
    _inc, old_gen = ext.state.generation()
    assert old_gen == 3
    ext.journal.write_checkpoint_sync(ext.checkpoint_doc())
    ext.journal.crash()
    ext.state.retire()

    ext2 = Extender(load_config(env=env))
    journal_mod.recover_extender(ext2, _ListApi(
        [{"metadata": {"name": it["name"],
                       "annotations": it["annotations"]}}
         for it in items],
        [_pod_obj(a) for a in allocs],
    ))
    inc2, gen2 = ext2.state.generation()
    assert gen2 >= old_gen
    assert inc2 != _inc
    d = ext2.state.allocs_since(old_cursor)
    assert "full" in d and len(d["full"]) == 3
    ext2.journal.close()
    ext2.state.retire()


def test_lifecycle_resync_rides_the_generation_log():
    """Churn waves through the sim's real release loop: ONE bootstrap
    full read, every later resync incremental, wire bytes O(Δ), and
    the releases actually land (mirror correctness end to end)."""
    cfg = load_config(env={"TPUKUBE_SNAPSHOT_AUDIT_RATE": "1.0"})
    with SimCluster(cfg) as c:
        for wave in range(4):
            names = [f"w{wave}-{i}" for i in range(3)]
            for n in names:
                c.schedule(c.make_pod(n, tpu=1))
            for n in names:
                c.complete_pod(n)
        stats = c._lifecycle.resync_stats()
        assert stats["full"] == 1, stats  # the bootstrap read only
        assert stats["incremental"] >= 4
        assert stats["bytes"] > 0
        assert stats["incremental_hit_ratio"] == 1.0
        assert c.extender.state.allocations() == []
        assert ledger_divergence(c) == []
        assert c.extender.snapshots.audit_divergences == 0


def test_lifecycle_resync_gap_falls_back_full_never_stale():
    """A generation log too small for the wave: the resync degrades to
    counted FULL reads and still releases everything."""
    cfg = load_config(env={"TPUKUBE_GENERATION_LOG_CAPACITY": "2"})
    with SimCluster(cfg) as c:
        c._lifecycle.check_once()  # burn the bootstrap full read
        names = [f"p-{i}" for i in range(6)]
        for n in names:
            c.schedule(c.make_pod(n, tpu=1))
        for n in names[:-1]:
            c.pods.pop(f"default/{n}")
        c._lifecycle.check_once()  # 6 commits >> capacity 2: gap
        stats = c._lifecycle.resync_stats()
        assert stats["full"] >= 2  # bootstrap + the gap fallback
        assert len(c.extender.state.allocations()) == 1
        assert ledger_divergence(c) == []


def test_lifecycle_resync_disabled_keeps_legacy_reads():
    cfg = load_config(env={"TPUKUBE_GENERATION_LOG_CAPACITY": "0"})
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=1))
        c.complete_pod("p")
        stats = c._lifecycle.resync_stats()
        assert stats == {"full": 0, "incremental": 0, "bytes": 0,
                         "incremental_hit_ratio": None}
        assert c.extender.state.allocations() == []


def test_federated_allocs_since_incremental_and_kill_fallback():
    """The sharded plane's federated resync: incremental against a
    stable replica set, merged FULL after a replica kill/restart —
    never a stale merge."""
    cfg = load_config(env={"TPUKUBE_PLANNER_REPLICAS": "2",
                           "TPUKUBE_BATCH_ENABLED": "1"})
    slices = {
        "s0": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
        "s1": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
    }
    with SimCluster(cfg, slices=slices, in_process=True) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"a-{i}", tpu=1))
        fed = c.extender.state
        d0 = fed.allocs_since(None)
        assert "full" in d0 and len(d0["full"]) == 4
        c.schedule(c.make_pod("late", tpu=1))
        c.complete_pod("a-0")
        d1 = fed.allocs_since(d0["cursor"])
        assert "adds" in d1, d1
        adds = {a.pod_key for a in d1["adds"]}
        assert "default/late" in adds
        assert "default/a-0" in d1["removes"]
        # replica death: the merged answer degrades to FULL
        c.crash_replica(1)
        d2 = fed.allocs_since(d1["cursor"])
        assert "full" in d2
        mirror = {a.pod_key: a for a in d2["full"]}
        truth = {a.pod_key: a for a in fed.allocations()}
        assert mirror == truth


def test_restart_replica_replays_journal_segment_warm(tmp_path):
    """ROADMAP sharding item (d): a journal-enabled replica's restart
    replays its own WAL segment (warm) instead of the full re-ingest;
    deleting the segment exercises the cold failure ladder."""
    cfg = load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_JOURNAL_ENABLED": "1",
        "TPUKUBE_JOURNAL_PATH": str(tmp_path / "wal.jsonl"),
    })
    slices = {
        "s0": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
        "s1": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
    }
    with SimCluster(cfg, slices=slices, in_process=True) as c:
        for i in range(6):
            c.schedule(c.make_pod(f"p-{i}", tpu=1))
        before = {a.pod_key: (a.node_name, tuple(a.device_ids))
                  for a in c.extender.state.allocations()}
        victim = 1
        victim_allocs = len(
            c.extender.replicas[victim].extender.state.allocations())
        assert victim_allocs > 0
        c.crash_replica(victim)
        restored = c.restart_replica(victim)
        assert c.extender.last_restart == {
            "replica": victim, "warm": True, "restored": restored}
        after = {a.pod_key: (a.node_name, tuple(a.device_ids))
                 for a in c.extender.state.allocations()}
        assert after == before
        assert ledger_divergence(c) == []

        # failure ladder: lose the segment -> cold re-ingest, same state
        c.crash_replica(victim)
        seg = f"{cfg.journal_path}.r{victim}"
        import os
        os.unlink(seg)
        if os.path.exists(seg + ".ckpt"):
            os.unlink(seg + ".ckpt")
        c.restart_replica(victim)
        assert c.extender.last_restart["warm"] is False
        after2 = {a.pod_key: (a.node_name, tuple(a.device_ids))
                  for a in c.extender.state.allocations()}
        assert after2 == before
        assert ledger_divergence(c) == []


# -- observability + config -------------------------------------------------

def test_ingest_and_resync_statusz_sections():
    from tpukube.obs.statusz import extender_statusz

    cfg = load_config(env={})
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=1))
        c.complete_pod("p")  # first resync: the bootstrap full read
        c.schedule(c.make_pod("q", tpu=1))
        c.complete_pod("q")  # second: rides the generation log
        doc = extender_statusz(c.extender, lifecycle=c._lifecycle)
        assert doc["ingest"]["enabled"] is True
        assert doc["ingest"]["nodes_total"] == len(c.nodes)
        assert doc["ingest"]["batches"] >= 1
        assert doc["resync"]["enabled"] is True
        assert doc["resync"]["incremental"] >= 1

    off = load_config(env={"TPUKUBE_BULK_INGEST_ENABLED": "0",
                           "TPUKUBE_GENERATION_LOG_CAPACITY": "0"})
    with SimCluster(off) as c:
        c.schedule(c.make_pod("p", tpu=1))
        doc = extender_statusz(c.extender, lifecycle=c._lifecycle)
        assert doc["ingest"] == {"enabled": False}
        assert doc["resync"] == {"enabled": False}


def test_ingest_resync_series_render_only_when_on():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.registry import DECLARED_SERIES

    for name in ("tpukube_ingest_nodes_total", "tpukube_ingest_seconds",
                 "tpukube_resync_full_total",
                 "tpukube_resync_incremental_total",
                 "tpukube_resync_bytes_total"):
        assert name in DECLARED_SERIES

    cfg = load_config(env={})
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=1))
        c.complete_pod("p")
        text = render_extender_metrics(c.extender,
                                       lifecycle=c._lifecycle)
        assert "tpukube_ingest_nodes_total" in text
        assert "tpukube_resync_incremental_total" in text
        assert "tpukube_resync_bytes_total" in text

    off = load_config(env={"TPUKUBE_BULK_INGEST_ENABLED": "0",
                           "TPUKUBE_GENERATION_LOG_CAPACITY": "0"})
    with SimCluster(off) as c:
        c.schedule(c.make_pod("p", tpu=1))
        text = render_extender_metrics(c.extender,
                                       lifecycle=c._lifecycle)
        assert "tpukube_ingest_" not in text
        assert "tpukube_resync_" not in text


def test_config_validation():
    with pytest.raises(ValueError, match="generation_log_capacity"):
        load_config(env={"TPUKUBE_GENERATION_LOG_CAPACITY": "-1"})
    cfg = load_config(env={"TPUKUBE_GENERATION_LOG_CAPACITY": "0",
                           "TPUKUBE_BULK_INGEST_ENABLED": "false"})
    assert cfg.generation_log_capacity == 0
    assert cfg.bulk_ingest_enabled is False

import pytest

from tpukube.core import codec
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    AllocResult,
    ChipInfo,
    Health,
    NodeInfo,
    PodGroup,
    PodInfo,
    TopologyCoord,
)


def _node() -> tuple[NodeInfo, MeshSpec]:
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    chips = [
        ChipInfo("chip-0", 0, TopologyCoord(0, 0, 0), hbm_bytes=16 << 30),
        ChipInfo(
            "chip-1", 1, TopologyCoord(1, 0, 0), hbm_bytes=16 << 30,
            health=Health.UNHEALTHY,
        ),
    ]
    return NodeInfo(name="host-0-0-0", chips=chips, shares_per_chip=2), mesh


def test_node_topology_roundtrip():
    node, mesh = _node()
    payload = codec.encode_node_topology(node, mesh)
    node2, mesh2 = codec.decode_node_topology(payload)
    assert mesh2 == mesh
    assert node2.name == node.name
    assert node2.shares_per_chip == 2
    assert len(node2.chips) == 2
    assert node2.chips[1].health is Health.UNHEALTHY
    assert node2.chips[0].coord == TopologyCoord(0, 0, 0)
    assert node2.chips[0].hbm_bytes == 16 << 30


def test_node_from_annotations_checks_name():
    node, mesh = _node()
    annos = codec.annotate_node(node, mesh)
    got = codec.node_from_annotations("host-0-0-0", annos)
    assert got is not None and got[0].name == "host-0-0-0"
    with pytest.raises(codec.CodecError):
        codec.node_from_annotations("other-node", annos)
    assert codec.node_from_annotations("n", {}) is None


def test_node_topology_rejects_bad_payloads():
    with pytest.raises(codec.CodecError):
        codec.decode_node_topology("not json")
    with pytest.raises(codec.CodecError):
        codec.decode_node_topology('{"v":99,"node":"n","mesh":{},"chips":[]}')


def test_alloc_roundtrip():
    a = AllocResult(
        pod_key="default/train-3",
        node_name="host-1-0-0",
        device_ids=["tpu-0", "tpu-1"],
        coords=[TopologyCoord(2, 0, 0), TopologyCoord(3, 0, 0)],
        env={"TPU_VISIBLE_CHIPS": "0,1"},
    )
    b = codec.decode_alloc(codec.encode_alloc(a))
    assert b == a


def test_pod_group_annotations_roundtrip():
    g = PodGroup(name="llama-train", min_member=16, shape=(4, 4, 1))
    annos = codec.pod_group_annotations(g)
    g2 = codec.pod_group_from_annotations(annos)
    assert g2 == g


def test_pod_group_shape_optional_and_padded():
    g = codec.pod_group_from_annotations(
        {codec.ANNO_POD_GROUP: "g", codec.ANNO_POD_GROUP_MIN_MEMBER: "4"}
    )
    assert g == PodGroup("g", 4, None)
    g = codec.pod_group_from_annotations(
        {
            codec.ANNO_POD_GROUP: "g",
            codec.ANNO_POD_GROUP_MIN_MEMBER: "4",
            codec.ANNO_POD_GROUP_SHAPE: "4x2",
        }
    )
    assert g.shape == (4, 2, 1)


def test_pod_group_absent():
    assert codec.pod_group_from_annotations({}) is None


def test_pod_group_bad_values():
    with pytest.raises(codec.CodecError):
        codec.pod_group_from_annotations(
            {codec.ANNO_POD_GROUP: "g", codec.ANNO_POD_GROUP_MIN_MEMBER: "lots"}
        )
    with pytest.raises(codec.CodecError):
        codec.pod_group_from_annotations(
            {
                codec.ANNO_POD_GROUP: "g",
                codec.ANNO_POD_GROUP_MIN_MEMBER: "2",
                codec.ANNO_POD_GROUP_SHAPE: "4xtwo",
            }
        )


def test_attach_group_idempotent():
    pod = PodInfo(
        name="p",
        annotations=codec.pod_group_annotations(PodGroup("g", 2)),
    )
    codec.attach_group(pod)
    assert pod.group == PodGroup("g", 2)
    pod.group = PodGroup("explicit", 9)
    codec.attach_group(pod)  # must not clobber an explicit group
    assert pod.group.name == "explicit"


def test_node_topology_bad_links_roundtrip():
    node, mesh = _node()
    node.bad_links = [(TopologyCoord(1, 0, 0), TopologyCoord(0, 0, 0))]
    payload = codec.encode_node_topology(node, mesh)
    node2, _ = codec.decode_node_topology(payload)
    # decode canonicalizes the pair (smaller endpoint first)
    assert node2.bad_links == [(TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0))]
    # absent field (older annotations) decodes to no bad links
    node.bad_links = []
    node3, _ = codec.decode_node_topology(codec.encode_node_topology(node, mesh))
    assert node3.bad_links == []


def test_node_topology_rejects_malformed_bad_links():
    node, mesh = _node()
    payload = codec.encode_node_topology(node, mesh)
    import json
    obj = json.loads(payload)
    obj["badLinks"] = [[[0, 0], [1, 0, 0]]]  # 2-element coord
    with pytest.raises(codec.CodecError, match="badLinks"):
        codec.decode_node_topology(json.dumps(obj))
    obj["badLinks"] = "nope"
    with pytest.raises(codec.CodecError, match="badLinks"):
        codec.decode_node_topology(json.dumps(obj))


def test_node_topology_rejects_out_of_mesh_or_nonadjacent_bad_links():
    """A stale annotation with an arbitrary coord pair must not flow into
    link-containment checks, where it would silently veto placements."""
    import json
    node, mesh = _node()
    obj = json.loads(codec.encode_node_topology(node, mesh))
    obj["badLinks"] = [[[0, 0, 0], [9, 0, 0]]]  # endpoint outside 4x4x1
    with pytest.raises(codec.CodecError, match="outside mesh"):
        codec.decode_node_topology(json.dumps(obj))
    obj["badLinks"] = [[[0, 0, 0], [2, 0, 0]]]  # in-mesh but not adjacent
    with pytest.raises(codec.CodecError, match="not ICI-adjacent"):
        codec.decode_node_topology(json.dumps(obj))
    obj["badLinks"] = [[[0, 0, 0], [0, 0, 0]]]  # degenerate self-link
    with pytest.raises(codec.CodecError, match="not ICI-adjacent"):
        codec.decode_node_topology(json.dumps(obj))


def test_node_topology_accepts_torus_wrap_bad_links():
    """On a torus axis, (0,y,z)<->(X-1,y,z) IS an ICI link and a fault on
    it must decode (the adjacency check is torus-aware)."""
    node, _ = _node()
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1),
                    torus=(True, False, False))
    node.bad_links = [(TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))]
    node2, _ = codec.decode_node_topology(codec.encode_node_topology(node, mesh))
    assert node2.bad_links == [(TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))]

"""ISSUE 17: capacity analytics & demand forensics plane —
flight recorder, stranded-demand root-causing, what-if probes.

The acceptance gates covered here:
  * the bounded flight recorder (ring capacity, JSONL sink rotation,
    FakeClock-compressed sampling cadence);
  * the stranded-demand taxonomy — one test per reason, including the
    fragmented-vs-capacity disambiguation on a hand-built torus where
    chips are free but no contiguous box exists;
  * what-if probe answers agree with the real planner's verdict on the
    same fleet (probe says fits ⇔ scheduling succeeds);
  * off-is-off: with ``capacity_enabled`` false (the default) nothing
    capacity-shaped reaches /metrics or /statusz, and the only series
    a capacity-on run adds are the declared capacity family;
  * federated merge: per-replica attribution survives the stitch and a
    dead replica degrades loudly (``dead_replicas``), never silently.
"""

from __future__ import annotations

import argparse
import json
import os

import pytest

from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.metrics import render_extender_metrics
from tpukube.obs.capacity import (
    UNSCHEDULABLE_REASONS,
    format_capacity,
    merge_capacity_docs,
    merge_probe_docs,
    parse_duration,
    parse_shape,
    parse_since,
)
from tpukube.obs.slo import parse_metrics
from tpukube.obs.statusz import extender_statusz
from tpukube.sched import kube
from tpukube.sim.harness import SimCluster


def cap_config(**extra: str):
    return load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_CAPACITY_ENABLED": "1",
        **extra,
    })


def _info(c: SimCluster, name: str, tpu: int = 1, group=None):
    """A PodInfo for the forensics seam, built through the same k8s
    conversion the webhook path uses."""
    return kube.pod_from_k8s(c.make_pod(name, tpu=tpu, group=group))


def _fragment(c: SimCluster) -> None:
    """Fill the 32-chip mesh with 1-chip pods, then complete every pod
    on an even x-plane: 16 chips free but the largest contiguous box is
    the 8-chip 1x4x2 plane — free ≠ placeable, the repack signal."""
    placed = {}
    for i in range(32):
        _, alloc = c.schedule(c.make_pod(f"fill-{i}", tpu=1))
        placed[f"fill-{i}"] = alloc
    for name, alloc in placed.items():
        if alloc.coords[0][0] % 2 == 0:
            c.complete_pod(name)


# -- duration / shape parsers (the shared --since seam) ----------------------

def test_parse_duration_suffixes_and_bare_floats():
    assert parse_duration("90") == 90.0
    assert parse_duration("90s") == 90.0
    assert parse_duration("15m") == 900.0
    assert parse_duration("2h") == 7200.0
    assert parse_duration("1d") == 86400.0
    assert parse_duration(" 1.5h ") == 5400.0
    assert parse_since("15m") == parse_duration("15m")
    for junk in ("", "m", "abc", "15q", "h2"):
        with pytest.raises(ValueError):
            parse_duration(junk)


def test_parse_shape():
    assert parse_shape("4x4x4") == (4, 4, 4)
    assert parse_shape("1X2x3") == (1, 2, 3)
    for junk in ("4x4", "4x4x4x4", "0x1x1", "axbxc"):
        with pytest.raises(ValueError):
            parse_shape(junk)


def test_cli_since_arg_wraps_parse_errors():
    from tpukube.cli import _since_arg

    assert _since_arg("15m") == 900.0
    assert _since_arg("42") == 42.0
    with pytest.raises(argparse.ArgumentTypeError):
        _since_arg("soon")


# -- flight recorder ---------------------------------------------------------

def test_ring_bounds_hold_under_overflow():
    cfg = cap_config(TPUKUBE_CAPACITY_SAMPLES="4")
    with SimCluster(cfg, clock=FakeClock()) as c:
        cap = c.extender.capacity
        assert cap is not None
        c.schedule(c.make_pod("a", tpu=1))
        base = cap.samples_taken  # handle() itself may have sampled
        for _ in range(10):
            cap.sample_now()
        assert cap.samples_taken == base + 10
        assert len(cap.ring) == 4 == cap.stats()["ring"]
        # the ring keeps the NEWEST samples, ordered
        clocks = [s["clock"] for s in cap.samples()]
        assert clocks == sorted(clocks)
        s = cap.samples()[-1]
        assert s["fleet"]["chips"] == 32
        assert s["fleet"]["free_chips"] == 31


def test_sink_rotation_caps_the_capture(tmp_path):
    path = str(tmp_path / "capacity.jsonl")
    cfg = cap_config(TPUKUBE_CAPACITY_PATH=path,
                     TPUKUBE_CAPACITY_SINK_MAX_BYTES="4096")
    with SimCluster(cfg, clock=FakeClock()) as c:
        cap = c.extender.capacity
        for _ in range(50):
            cap.sample_now()
        cap.close()
        stats = cap.stats()["sink"]
        assert stats["path"] == path
        assert stats["rotations"] >= 1
        assert os.path.getsize(path) <= 4096
        assert os.path.exists(path + ".1")
        # every surviving line is a whole JSON sample — rotation must
        # never split or concatenate lines
        lines = open(path).read().splitlines()
        assert lines
        for line in lines:
            assert "fleet" in json.loads(line)


def test_fake_clock_sampling_cadence():
    """maybe_sample rides the SCHEDULING clock: repeated calls inside
    one interval take one sample; advancing the FakeClock unlocks the
    next — hours of cadence compress wall-free."""
    clock = FakeClock()
    with SimCluster(cap_config(), clock=clock) as c:
        cap = c.extender.capacity
        for _ in range(5):
            cap.maybe_sample()
        assert cap.samples_taken == 1
        clock.advance(29.0)  # default interval is 30s
        cap.maybe_sample()
        assert cap.samples_taken == 1
        clock.advance(1.0)
        cap.maybe_sample()
        assert cap.samples_taken == 2
        for h in range(4):
            clock.advance(3600.0)
            cap.maybe_sample()
        assert cap.samples_taken == 6


def test_samples_since_window_clips_by_wall_ts():
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        for _ in range(3):
            cap.sample_now()
        cut = cap.samples()[1]["ts"]
        assert len(cap.samples(since=cut)) == 2
        assert cap.samples(since=cut + 10.0) == []


# -- stranded-demand forensics: the taxonomy ---------------------------------

def test_taxonomy_quota_and_shed_are_string_routed():
    """Tenancy refusals carry their own verdict — the plane refused,
    geometry did not, so no geometric re-probe may overrule them."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        cap.note_refusal(_info(c, "q"), "tenant team-a quota exceeded")
        cap.note_refusal(_info(c, "s"), "admission shed: burn rate")
        counts = cap.unschedulable_counts()
        assert counts == {"quota": 1, "shed": 1}
        by_reason = cap.stranded_by_reason()
        assert by_reason["quota"] == (1, 1)
        assert by_reason["shed"] == (1, 1)


def test_taxonomy_capacity_when_no_chips_anywhere():
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)  # 16 free
        grp = PodGroup("big", min_member=24)
        cap.note_failed_plan(_info(c, "big-0", group=grp))
        assert cap.unschedulable_counts() == {"capacity": 1}
        rows = cap.stranded_summary()["by_shape"]
        assert rows == [{"shape": "24", "demands": 1,
                         "chips_requested": 24,
                         "reasons": {"capacity": 1}}]


def test_taxonomy_unhealthy_when_healing_would_cover():
    """free < demand but free-if-healed >= demand: the root cause is
    the unhealthy chip, not fleet size — a repair ticket, not a
    capacity buy."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        c.inject_fault("host-0-0-0", 0)
        c.schedule(c.make_pod("sync", tpu=1))  # re-ingests the fault
        # 32 chips: 1 unhealthy + 1 allocated -> 30 free, 31 if healed
        cap.note_failed_plan(_info(c, "ask31", tpu=31))
        assert cap.unschedulable_counts() == {"unhealthy": 1}


def test_taxonomy_fragmented_vs_capacity_on_a_torus():
    """The disambiguation the defragmenter pivots on, on a hand-built
    torus: 16 chips free in two non-adjacent x-planes (the x-wraparound
    does not join them — the occupied odd planes separate them even on
    the ring). A 16-chip gang is FRAGMENTED (chips exist, repack
    recovers them); a 24-chip gang is CAPACITY (no repack can mint
    chips)."""
    cfg = load_config(env={"TPUKUBE_CAPACITY_ENABLED": "1"})
    mesh = MeshSpec(dims=(4, 4, 2), host_block=(2, 2, 1),
                    torus=(True, True, False))
    with SimCluster(cfg, mesh=mesh, clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)
        grp = PodGroup("frag", min_member=16)
        cap.note_failed_plan(_info(c, "frag-0", group=grp))
        grp2 = PodGroup("toobig", min_member=24)
        cap.note_failed_plan(_info(c, "toobig-0", group=grp2))
        assert cap.unschedulable_counts() == {
            "fragmented": 1, "capacity": 1,
        }
        by_reason = cap.stranded_by_reason()
        assert by_reason["fragmented"] == (1, 16)
        assert by_reason["capacity"] == (1, 24)
        # the fragmented detail quantifies the repack upside:
        # 16 free - the 8-chip largest box = 8 recoverable
        rollup = cap.stranded_summary()
        assert rollup["recoverable_chips"] == 8


def test_taxonomy_transient_when_failure_no_longer_reproduces():
    """A demand that fits by re-probe time classifies transient —
    honest about the race, never a fabricated root cause."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        c.schedule(c.make_pod("a", tpu=1))
        cap.note_failed_plan(_info(c, "fits", tpu=2))
        assert cap.unschedulable_counts() == {"transient": 1}


def test_taxonomy_dcn_ineligible_vs_dcn_covered():
    """Two slices, neither holds the whole gang: without the DCN
    opt-in the demand is dcn-ineligible (spanning is the only serve);
    with allow_dcn the greedy split covers it and the verdict is the
    honest transient."""
    cfg = load_config(env={"TPUKUBE_CAPACITY_ENABLED": "1"})
    slices = {
        sid: MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                      torus=(False, False, False))
        for sid in ("s0", "s1")
    }
    with SimCluster(cfg, slices=slices, clock=FakeClock()) as c:
        cap = c.extender.capacity
        # fill both slices, then free each slice's contiguous z=1
        # layer: 4 free per slice (one 2x2x1 box each), 8 fleet-wide —
        # no single slice can hold the 8-chip gang
        placed = {}
        for i in range(16):
            _, alloc = c.schedule(c.make_pod(f"fill-{i}", tpu=1))
            placed[f"fill-{i}"] = alloc
        for name, alloc in placed.items():
            if alloc.coords[0][2] == 1:
                c.complete_pod(name)
        no_dcn = PodGroup("span", min_member=8)
        cap.note_failed_plan(_info(c, "span-0", group=no_dcn))
        assert cap.unschedulable_counts() == {"dcn-ineligible": 1}
        dcn = PodGroup("span2", min_member=8, allow_dcn=True)
        cap.note_failed_plan(_info(c, "span2-0", group=dcn))
        assert cap.unschedulable_counts() == {
            "dcn-ineligible": 1, "transient": 1,
        }


def test_gang_refusal_storm_is_one_ledger_row():
    """128 refusals of one gang against one snapshot epoch: the
    counter bills every refusal, the geometric probe runs ONCE, and
    the ledger keeps one demand row."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)
        grp = PodGroup("storm", min_member=16)
        for i in range(128):
            cap.note_failed_plan(_info(c, f"storm-{i}", group=grp))
        assert cap.classified == 1
        assert cap.unschedulable_counts() == {"fragmented": 128}
        rollup = cap.stranded_summary()
        assert rollup["demands"] == 1
        assert rollup["chips_requested"] == 16


def test_stranded_ledger_expires_stale_demands():
    """Without a batch queue to consult, TTL retires a row — a
    stranded entry must never outlive the demand it names."""
    clock = FakeClock()
    with SimCluster(cap_config(), clock=clock) as c:
        cap = c.extender.capacity
        _fragment(c)
        grp = PodGroup("old", min_member=16)
        cap.note_failed_plan(_info(c, "old-0", group=grp))
        assert cap.stranded_summary()["demands"] == 1
        clock.advance(901.0)
        assert cap.stranded_summary()["demands"] == 0
        # cumulative counters are history, not liveness: they survive
        assert cap.unschedulable_counts() == {"fragmented": 1}


def test_refused_webhook_pod_lands_in_forensics():
    """The legacy (non-batch) seam end-to-end: a real gang refusal
    through the webhook filter classifies without anyone calling the
    recorder explicitly."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        _fragment(c)
        grp = PodGroup("stuck", min_member=16)
        with pytest.raises(RuntimeError):
            c.schedule(c.make_pod("stuck-0", tpu=1, group=grp))
        counts = c.extender.capacity.unschedulable_counts()
        assert counts.get("fragmented", 0) >= 1


# -- what-if probes ----------------------------------------------------------

def test_probe_parity_with_planner_verdict():
    """probe() and the planner answer the same question the same way:
    fits=False ⇔ scheduling raises, fits=True ⇔ scheduling places."""
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)
        no16 = cap.probe(count=16)
        assert not no16["fits"]
        assert no16["free_chips"] == 16
        assert no16["largest_free_box"] == 8
        assert not no16["dcn"]["fits"]
        grp = PodGroup("gang16", min_member=16)
        with pytest.raises(RuntimeError):
            c.schedule(c.make_pod("gang16-0", tpu=1, group=grp))
        yes8 = cap.probe(count=8)
        assert yes8["fits"] and yes8["slice"] is not None
        shape8 = cap.probe(shape=(1, 4, 2))
        assert shape8["fits"]
        assert shape8["requested"]["chips"] == 8
        # the planner agrees: an 8-member gang lands in each of the
        # two free planes
        for g in ("gang8a", "gang8b"):
            grp8 = PodGroup(g, min_member=8)
            for i in range(8):
                c.schedule(c.make_pod(f"{g}-{i}", tpu=1, group=grp8))
        # and once the planner consumed both boxes, the probe flips
        assert not cap.probe(count=8)["fits"]
        assert cap.probe(count=8)["free_chips"] == 0


def test_probe_validates_its_ask():
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        with pytest.raises(ValueError):
            cap.probe()
        with pytest.raises(ValueError):
            cap.probe(count=4, shape=(1, 2, 2))
        with pytest.raises(ValueError):
            cap.probe(count=0)


# -- off-is-off --------------------------------------------------------------

def test_capacity_off_leaves_exposition_untouched():
    """Default config: no recorder is constructed and nothing
    capacity-shaped reaches /metrics or /statusz."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg, clock=FakeClock()) as c:
        c.schedule(c.make_pod("a", tpu=1))
        assert c.extender.capacity is None
        text = render_extender_metrics(c.extender)
        assert "tpukube_capacity" not in text
        assert "tpukube_unschedulable_pods" not in text
        doc = extender_statusz(c.extender)
        assert "capacity" not in doc


def test_capacity_on_adds_exactly_the_declared_family():
    """The same workload with capacity on adds the capacity series —
    and ONLY them: the legacy series set is unchanged, so the off
    exposition stays byte-identical by construction."""
    def series_names(enabled: bool) -> set[str]:
        env = {"TPUKUBE_SIM_MESH_DIMS": "4,4,2",
               "TPUKUBE_SIM_HOST_BLOCK": "2,2,1"}
        if enabled:
            env["TPUKUBE_CAPACITY_ENABLED"] = "1"
        with SimCluster(load_config(env=env), clock=FakeClock()) as c:
            c.schedule(c.make_pod("a", tpu=1))
            if enabled:
                c.extender.capacity.sample_now()
            return {s.name for s in
                    parse_metrics(render_extender_metrics(c.extender))}

    off, on = series_names(False), series_names(True)
    assert off <= on
    assert on - off == {
        "tpukube_capacity_samples_total",
        "tpukube_capacity_sample_seconds_total",
        "tpukube_capacity_fleet_chips",
        "tpukube_capacity_stranded_chips",
        "tpukube_capacity_stranded_demands",
        "tpukube_capacity_recoverable_chips",
        "tpukube_unschedulable_pods",
    }


def test_capacity_on_statusz_and_reason_labels():
    with SimCluster(cap_config(), clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)
        grp = PodGroup("g", min_member=16)
        cap.note_failed_plan(_info(c, "g-0", group=grp))
        cap.sample_now()
        doc = extender_statusz(c.extender)
        assert doc["capacity"]["samples"] == cap.samples_taken >= 1
        assert doc["capacity"]["stranded"]["demands"] == 1
        text = render_extender_metrics(c.extender)
        # every taxonomy reason renders (zero-filled), the fragmented
        # one carries the count
        for reason in UNSCHEDULABLE_REASONS:
            assert f'reason="{reason}"' in text
        assert ('tpukube_unschedulable_pods{reason="fragmented"} 1'
                in text)
        assert "tpukube_capacity_stranded_chips" in text


def test_queue_age_histogram_renders_with_batching_only():
    """The tpukube_cycle_queue_age_seconds satellite: a real
    _bucket/_count histogram with batching on, absent otherwise."""
    env = {"TPUKUBE_SIM_MESH_DIMS": "4,4,2",
           "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
           "TPUKUBE_BATCH_ENABLED": "1"}
    with SimCluster(load_config(env=env), clock=FakeClock()) as c:
        c.schedule_pending([c.make_pod(f"p-{i}", tpu=1)
                            for i in range(4)])
        text = render_extender_metrics(c.extender)
    assert "tpukube_cycle_queue_age_seconds_bucket" in text
    assert 'le="+Inf"' in text
    env.pop("TPUKUBE_BATCH_ENABLED")
    with SimCluster(load_config(env=env), clock=FakeClock()) as c:
        c.schedule(c.make_pod("solo", tpu=1))
        assert "tpukube_cycle_queue_age_seconds" not in \
            render_extender_metrics(c.extender)


# -- federation --------------------------------------------------------------

def _doc(ts, shape, reason, chips, samples_stats=None):
    return {
        "samples": [{"ts": ts, "clock": ts,
                     "fleet": {"utilization": 0.5}}],
        "stranded": {
            "demands": 1, "chips_requested": chips,
            "recoverable_chips": chips // 2,
            "by_shape": [{"shape": shape, "demands": 1,
                          "chips_requested": chips,
                          "reasons": {reason: 1}}],
        },
        "unschedulable": {reason: 1},
        "stats": samples_stats or {"samples": 1},
    }


def test_merge_keeps_attribution_and_names_the_dead():
    merged = merge_capacity_docs([
        ("r0", _doc(2.0, "64", "fragmented", 64)),
        ("r1", _doc(1.0, "64", "capacity", 64)),
        ("r2", None),
    ])
    assert merged["dead_replicas"] == ["r2"]
    # samples interleave by wall ts, each stamped with its source
    assert [(s["ts"], s["replica"]) for s in merged["samples"]] == \
        [(1.0, "r1"), (2.0, "r0")]
    row = merged["stranded"]["by_shape"][0]
    assert row["demands"] == 2
    assert row["reasons"] == {"fragmented": 1, "capacity": 1}
    assert row["replicas"] == {"r0": 1, "r1": 1}
    assert merged["stranded"]["recoverable_chips"] == 64
    assert merged["unschedulable"] == {"fragmented": 1, "capacity": 1}
    assert set(merged["stats"]) == {"r0", "r1"}


def test_merge_probe_any_whole_fit_wins_and_dcn_composes():
    fit = {"free_chips": 8, "largest_free_box": 8, "fits": True,
           "slice": "s1", "slices": {"s1": {"fits": True}},
           "dcn": {"fits": True, "parts": {"s1": 8}}}
    nofit = {"free_chips": 4, "largest_free_box": 4, "fits": False,
             "slice": None, "slices": {"s0": {"fits": False}},
             "dcn": {"fits": False, "parts": {}}}
    merged = merge_probe_docs(
        [("r0", nofit), ("r1", fit), ("r2", None)],
        {"count": 8, "shape": None, "chips": 8})
    assert merged["fits"] and merged["replica"] == "r1"
    assert merged["slice"] == "s1"
    assert merged["free_chips"] == 12
    assert merged["dead_replicas"] == ["r2"]
    assert merged["slices"]["s0"]["replica"] == "r0"
    # no replica fits it whole -> the composed DCN verdict remains
    merged2 = merge_probe_docs(
        [("r0", nofit), ("r1", None)],
        {"count": 8, "shape": None, "chips": 8})
    assert not merged2["fits"]
    assert merged2["dead_replicas"] == ["r1"]


def test_router_capacity_doc_degrades_loudly_on_dead_replica():
    """The in-process sharded plane: /capacity federates both
    replicas' forensics with attribution; partitioning one away turns
    it into a named dead replica — a partial fleet view is never
    served as whole."""
    cfg = load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_CAPACITY_ENABLED": "1",
    })
    slices = {
        sid: MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                      torus=(False, False, False))
        for sid in ("s0", "s1")
    }
    with SimCluster(cfg, in_process=True, slices=slices,
                    clock=FakeClock()) as c:
        c.schedule_pending([c.make_pod(f"p-{i}", tpu=1)
                            for i in range(4)])
        router = c.extender
        for rep in router.replicas:
            rep.transport.extender.capacity.sample_now()
        doc = router.capacity_doc()
        assert doc["dead_replicas"] == []
        assert set(doc["stats"]) == {"r0", "r1"}
        assert {s["replica"] for s in doc["samples"]} == {"r0", "r1"}
        probe = router.capacity_probe(count=4)
        assert probe["fits"]
        c.partition_replica(1)
        doc2 = router.capacity_doc()
        assert doc2["dead_replicas"] == ["r1"]
        assert set(doc2["stats"]) == {"r0"}
        text = format_capacity(doc2)
        assert "WARNING: no capacity answer from replica(s) r1" in text


def test_sole_router_serves_the_extender_doc_verbatim():
    """N=1: the router's /capacity IS the sole extender's document —
    no merge wrapper, no dead_replicas key, byte-identical off-is-off
    with the federated plane too."""
    from tpukube.sched.shard import ShardRouter

    router = ShardRouter(load_config(env={
        "TPUKUBE_CAPACITY_ENABLED": "1",
    }))
    assert router._sole is not None
    router._sole.capacity.sample_now()
    doc = router.capacity_doc()
    assert doc == router._sole.capacity.capacity_doc()
    assert "dead_replicas" not in doc
    off = ShardRouter(load_config(env={}))
    assert off.capacity_doc() is None
    assert off.capacity_probe(count=4) is None


# -- rendering ---------------------------------------------------------------

def test_format_capacity_sparkline_csv_json():
    doc = merge_capacity_docs([
        ("r0", _doc(2.0, "64", "fragmented", 64)),
        ("r1", None),
    ])
    spark = format_capacity(doc)
    assert "utilization" in spark
    assert "stranded: 1x 64-chip demand(s) (1x fragmented)" in spark
    assert "64 chips requested [r0: 1]" in spark
    assert "32 chips recoverable by repack" in spark
    assert "unschedulable plans: fragmented=1" in spark
    assert "WARNING: no capacity answer from replica(s) r1" in spark
    csv = format_capacity(doc, "csv")
    assert csv.splitlines()[0].startswith("ts,replica,utilization")
    assert len(csv.splitlines()) == 2
    assert json.loads(format_capacity(doc, "json")) == doc


def test_explain_chain_carries_the_stranded_stage():
    """With provenance on, a classified demand lands in the pod's
    explain chain naming the root cause — `tpukube-obs explain` tells
    the operator WHY the gang is stuck, not just that it is."""
    cfg = cap_config(TPUKUBE_DECISIONS_ENABLED="1",
                     TPUKUBE_DECISIONS_SAMPLE_RATE="1.0")
    with SimCluster(cfg, clock=FakeClock()) as c:
        cap = c.extender.capacity
        _fragment(c)
        grp = PodGroup("stuck", min_member=16)
        cap.note_failed_plan(_info(c, "stuck-0", group=grp))
        doc = c.extender.decisions.explain("default/stuck-0")
        from tpukube.obs.decisions import explain_doc
        rendered = explain_doc(doc["events"], "default/stuck-0") \
            if isinstance(doc, dict) and "events" in doc else doc
        stages = [ev for ev in rendered["stages"]
                  if ev.get("stage") == "stranded"]
        assert stages and stages[0]["reason"] == "fragmented"
        why = "\n".join(rendered["why"])
        assert "root cause fragmented" in why

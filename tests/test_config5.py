"""BASELINE config 5: multi-tenant — 64-pod Llama-3-70B train gang + burst
inference pods on a v5p-128-scale mesh (128 chips, 32 hosts): bin-packing,
priority preemption, and the two north-star metrics.

North star (BASELINE.md): >= 95% cluster chip utilization with the 64-pod
gang placed ICI-contiguously; p50 gang-schedule latency measured.
"""

import json
import urllib.request

import pytest

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster


@pytest.fixture(scope="module")
def loaded_cluster():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        yield c


def test_config5_multi_tenant_preemption_and_utilization(loaded_cluster):
    c = loaded_cluster

    # phase 1: burst inference load — 80 single-chip pods at priority 0
    for i in range(80):
        c.schedule(c.make_pod(f"infer-{i}", tpu=1, priority=0))
    assert c.utilization() == pytest.approx(80 / 128)

    # phase 2: the 64-pod training gang arrives at high priority; no free
    # contiguous 64-chip box exists, so it must preempt burst pods
    group = PodGroup("llama-70b", min_member=64)
    allocs = []
    for i in range(64):
        node, alloc = c.schedule(
            c.make_pod(f"train-{i}", tpu=1, priority=100, group=group)
        )
        allocs.append(alloc)

    res = c.extender.gang.reservation("default", "llama-70b")
    assert res.committed
    assert c.extender.preemptions > 0, "gang landed without preemption?"

    # ICI-contiguity of the 64-chip slice
    coords = sorted(co for a in allocs for co in a.coords)
    assert len(set(coords)) == 64
    xs = sorted({c_[0] for c_ in coords})
    ys = sorted({c_[1] for c_ in coords})
    zs = sorted({c_[2] for c_ in coords})
    assert len(xs) * len(ys) * len(zs) == 64
    for axis_vals in (xs, ys, zs):
        assert axis_vals == list(range(axis_vals[0], axis_vals[0] + len(axis_vals)))

    # phase 3: evicted burst pods (auto-drained from the pod store during
    # the gang's scheduling cycles) get rescheduled onto remaining chips
    evicted = [
        f"infer-{i}" for i in range(80)
        if c.extender.state.allocation(f"default/infer-{i}") is None
    ]
    assert evicted, "preemption evicted no burst pods"
    assert all(f"default/{name}" not in c.pods for name in evicted), (
        "evicted pods were not removed from the pod store"
    )
    rescheduled = 0
    for name in evicted:
        try:
            c.schedule(c.make_pod(f"{name}-retry", tpu=1, priority=0))
            rescheduled += 1
        except RuntimeError:
            break  # cluster full — remaining burst pods stay Pending
    # fill any remaining capacity with fresh burst arrivals
    while True:
        try:
            c.schedule(c.make_pod(f"fill-{rescheduled}", tpu=1, priority=0))
            rescheduled += 1
        except RuntimeError:
            break

    # ---- north star #1: utilization ------------------------------------
    util = c.utilization()
    assert util >= 0.95, f"north-star utilization {util:.2%} < 95%"

    # ---- north star #2: gang latency from the live /metrics endpoint ---
    with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert "tpu_chip_utilization_percent" in text
    lines = {
        l.split(" ")[0]: float(l.split(" ")[1])
        for l in text.splitlines()
        if l and not l.startswith("#")
    }
    assert lines["tpu_chip_utilization_percent"] >= 95.0
    p50 = lines['gang_schedule_latency_seconds{quantile="0.5"}']
    assert 0 < p50 < 60, f"implausible gang p50 {p50}"
    assert lines["tpukube_preemptions_total"] > 0
    print(
        f"\nNORTH STAR: utilization={lines['tpu_chip_utilization_percent']:.1f}% "
        f"gang_p50={p50 * 1000:.1f}ms "
        f"preemptions={int(lines['tpukube_preemptions_total'])}"
    )


def test_config5_low_priority_gang_cannot_preempt(loaded_cluster):
    c = loaded_cluster  # cluster is ~full from the previous test
    group = PodGroup("freeloader", min_member=32)
    with pytest.raises(RuntimeError, match="cannot preempt|no victim set|no contiguous"):
        c.schedule(c.make_pod("fl-0", tpu=1, priority=0, group=group))


def test_config5_gang_victims_die_whole():
    # preemption never evicts individual members of a gang: the victim is
    # the entire gang (all-or-nothing in death as in birth)
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        low = PodGroup("low", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"lo-{i}", tpu=1, priority=10, group=low))
        for i in range(8):
            c.schedule(c.make_pod(f"solo-{i}", tpu=1, priority=10))
        # a prio-50 4-chip gang: cheapest contiguous box costs 4 solo pods
        # (cost 40) vs the whole low gang (cost 80) — solos must die first
        vip = PodGroup("vip", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"vp-{i}", tpu=1, priority=50, group=vip))
        assert all(
            c.extender.state.allocation(f"default/lo-{i}") is not None
            for i in range(8)
        ), "gang was partially or wholly evicted though solos were cheaper"
        # now a prio-60 8-chip gang arrives; only the low gang's box fits —
        # it must be dissolved wholesale, never member-by-member
        big = PodGroup("big", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"bg-{i}", tpu=1, priority=60, group=big))
        low_alive = [
            i for i in range(8)
            if c.extender.state.allocation(f"default/lo-{i}") is not None
        ]
        assert low_alive == [], f"partial gang survival: {low_alive}"
        assert c.extender.gang.reservation("default", "low") is None


def test_config5_preemption_chooses_cheapest_victims():
    # two victim populations: cheap (prio 1) on one half, expensive (prio
    # 50) on the other; a prio-100 gang must evict from the cheap half
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_SCORE_MODE": "binpack",
    })
    with SimCluster(cfg) as c:
        # fill left half (x<2) with cheap, right half with expensive pods
        for i in range(16):
            c.schedule(c.make_pod(f"p-{i}", tpu=1,
                                  priority=1 if i < 8 else 50))
        # verify the halves actually split by checking a sample... binpack
        # fills host by host deterministically: hosts 0,1 get p-0..7
        group = PodGroup("vip", min_member=8, shape=(2, 4, 1))
        for i in range(8):
            c.schedule(c.make_pod(f"v-{i}", tpu=1, priority=100, group=group))
        res = c.extender.gang.reservation("default", "vip")
        assert res.committed
        # every surviving allocation of the original 16 is expensive
        survivors = {
            k: c.extender.state.priority_of(k)
            for k in (f"default/p-{i}" for i in range(16))
            if c.extender.state.allocation(k) is not None
        }
        assert len(survivors) == 8, survivors
        assert all(p == 50 for p in survivors.values()), survivors

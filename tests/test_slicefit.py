"""Property tests for the slicefit allocator (SURVEY.md §5: pure functions
over synthetic mesh states, the grpalloc-test analog)."""

import random

import numpy as np
import pytest

from tpukube.core.mesh import Box, MeshSpec
from tpukube.core.types import TopologyCoord
from tpukube.sched.slicefit import (
    find_slice,
    fragmentation,
    iter_free_boxes,
    occupancy_grid,
)

MESH = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))


def _exhaustive_has_box(mesh, occupied, count):
    """Oracle: brute-force search for any fully-free box of volume count."""
    occ = set(occupied)
    X, Y, Z = mesh.dims
    for a in range(1, X + 1):
        for b in range(1, Y + 1):
            for c in range(1, Z + 1):
                if a * b * c != count:
                    continue
                for ox in range(X - a + 1):
                    for oy in range(Y - b + 1):
                        for oz in range(Z - c + 1):
                            box = Box(TopologyCoord(ox, oy, oz), (a, b, c))
                            if all(co not in occ for co in box.coords()):
                                return True
    return False


def test_empty_mesh_full_slice():
    coords = find_slice(MESH, [], count=64)
    assert coords is not None and len(coords) == 64
    assert set(coords) == set(MESH.all_coords())


def test_exact_shape_honored_up_to_permutation():
    coords = find_slice(MESH, [], shape=(1, 4, 2))
    assert coords is not None and len(coords) == 8
    xs = {c.x for c in coords}
    ys = {c.y for c in coords}
    zs = {c.z for c in coords}
    assert sorted([len(xs), len(ys), len(zs)]) == [1, 2, 4]


def test_no_overlap_with_occupied_randomized():
    rng = random.Random(7)
    for trial in range(50):
        occupied = {
            c for c in MESH.all_coords() if rng.random() < rng.choice([0.2, 0.5, 0.8])
        }
        n = rng.choice([1, 2, 4, 8, 16])
        coords = find_slice(MESH, occupied, count=n)
        if coords is None:
            assert not _exhaustive_has_box(MESH, occupied, n), (
                f"trial {trial}: solver missed an existing box"
            )
        else:
            assert len(coords) == n
            assert not (set(coords) & occupied), f"trial {trial}: overlap"
            assert all(MESH.contains(c) for c in coords)


def test_finds_box_iff_exists_oracle():
    # deterministic tight case: occupy everything except one 2x2x1 corner
    free = {TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0),
            TopologyCoord(0, 1, 0), TopologyCoord(1, 1, 0)}
    occupied = set(MESH.all_coords()) - free
    assert set(find_slice(MESH, occupied, count=4)) == free
    assert find_slice(MESH, occupied, count=8) is None
    assert find_slice(MESH, occupied, shape=(4, 1, 1)) is None
    assert find_slice(MESH, occupied, shape=(2, 2, 1)) is not None


def test_compactness_preferred_over_sliver():
    # 16 chips on an empty 4x4x4: a 4x2x2 (surface 40) must beat 4x4x1 (48)
    coords = find_slice(MESH, [], count=16)
    dims = tuple(
        len({getattr(c, ax) for c in coords}) for ax in ("x", "y", "z")
    )
    assert sorted(dims) == [2, 2, 4]


def test_corner_packing_on_empty_mesh():
    # with all else equal, the box should hug a corner (max wall contact)
    coords = find_slice(MESH, [], count=4)
    assert TopologyCoord(0, 0, 0) in coords


def test_snug_placement_next_to_occupied():
    # occupy the x=0 plane; a 2x2x1 request should nestle against it rather
    # than float in open space
    occupied = {c for c in MESH.all_coords() if c.x == 0}
    coords = find_slice(MESH, occupied, count=4)
    assert coords is not None
    assert any(c.x == 1 for c in coords)  # touching the occupied plane


def test_determinism():
    rng = random.Random(3)
    occupied = {c for c in MESH.all_coords() if rng.random() < 0.4}
    a = find_slice(MESH, occupied, count=8)
    b = find_slice(MESH, occupied, count=8)
    assert a == b


def test_irregular_fallback():
    # 5 chips in a 4x4x1 mesh: 5x1x1 does not fit, no other 5-volume box
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    assert find_slice(mesh, [], count=5) is None
    coords = find_slice(mesh, [], count=5, allow_irregular=True)
    assert coords is not None and len(coords) == 5
    # connected: every chip reachable from the first via free-set adjacency
    chosen = set(coords)
    seen = {coords[0]}
    frontier = [coords[0]]
    while frontier:
        nxt = [n for f in frontier for n in mesh.neighbors(f)
               if n in chosen and n not in seen]
        seen.update(nxt)
        frontier = nxt
    assert seen == chosen


def test_irregular_fallback_insufficient_space():
    mesh = MeshSpec(dims=(2, 2, 1), host_block=(1, 1, 1))
    occupied = [TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)]
    assert find_slice(mesh, occupied, count=3, allow_irregular=True) is None


def test_occupancy_grid_rejects_out_of_mesh():
    with pytest.raises(ValueError, match="outside mesh"):
        occupancy_grid(MESH, [TopologyCoord(9, 0, 0)])


def test_iter_free_boxes_requires_exactly_one_request_kind():
    grid = occupancy_grid(MESH, [])
    with pytest.raises(ValueError):
        list(iter_free_boxes(MESH, grid))
    with pytest.raises(ValueError):
        list(iter_free_boxes(MESH, grid, count=4, shape=(2, 2, 1)))


def test_torus_wrapped_box_found():
    # 4-ring with x=1 occupied: the contiguous 3-slice wraps {2,3,0}
    mesh = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1), torus=(True, False, False))
    occupied = [TopologyCoord(1, 0, 0)]
    coords = find_slice(mesh, occupied, shape=(3, 1, 1))
    assert coords is not None
    assert set(coords) == {TopologyCoord(2, 0, 0), TopologyCoord(3, 0, 0),
                           TopologyCoord(0, 0, 0)}
    # without torus the same request is unsatisfiable
    mesh_flat = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1))
    assert find_slice(mesh_flat, occupied, shape=(3, 1, 1)) is None


def test_torus_full_ring_canonical_and_no_overlap():
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1), torus=(True, True, False))
    # full-x-extent rows on a torus: all origins name the same chips; the
    # solver must still place two 4x1x1 jobs without overlap
    a = find_slice(mesh, [], shape=(4, 1, 1))
    b = find_slice(mesh, a, shape=(4, 1, 1))
    assert a and b and not (set(a) & set(b))


def test_torus_no_fictitious_wall_preference():
    # on a full torus every placement of a given shape is equivalent; the
    # solver must not crash crediting walls and must stay deterministic
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1), torus=(True, True, False))
    a = find_slice(mesh, [], count=4)
    assert a == find_slice(mesh, [], count=4)
    # snugness still honored against real occupancy on the torus
    occupied = {c for c in mesh.all_coords() if c.x == 2}
    got = find_slice(mesh, occupied, count=4)
    assert got is not None and not (set(got) & occupied)


def test_fragmentation_metric():
    assert fragmentation(MESH, []) == 0.0  # one perfect free box
    # checkerboard the mesh: free space shatters into 1x1x1 islands
    occupied = {c for c in MESH.all_coords() if (c.x + c.y + c.z) % 2}
    f = fragmentation(MESH, occupied)
    assert f == 1.0 - 1 / 32
    # fully occupied: defined as 0
    assert fragmentation(MESH, list(MESH.all_coords())) == 0.0


def test_large_mesh_performance():
    # v5p-128-scale sweep stays fast: 8x8x16 = 1024 chips (wall clock is
    # asserted loosely; the point is no exponential blowup)
    import time

    mesh = MeshSpec(dims=(8, 8, 16), host_block=(2, 2, 1))
    # structured load: half the mesh holds existing jobs, plus scattered
    # singles in part of the free half (random occupancy would make a free
    # 64-box astronomically unlikely — not a real cluster state)
    rng = random.Random(1)
    occupied = {c for c in mesh.all_coords() if c.z < 8}
    occupied |= {c for c in mesh.all_coords() if c.z >= 12 and rng.random() < 0.3}
    t0 = time.monotonic()
    coords = find_slice(mesh, occupied, count=64)
    dt = time.monotonic() - t0
    assert coords is not None and len(coords) == 64
    assert not (set(coords) & occupied)
    assert dt < 2.0, f"slicefit took {dt:.2f}s on 1024-chip mesh"


# -- ICI link faults (SURVEY.md §6: drop ICI link) ---------------------------

def test_broken_link_steers_box_choice():
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    # a downed link in the left half: the 2x2 box must land clear of it
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(0, 1, 0))}
    coords = find_slice(mesh, [], count=4, broken=broken)
    assert coords is not None and len(coords) == 4
    cs = set(coords)
    assert not (TopologyCoord(0, 0, 0) in cs and TopologyCoord(0, 1, 0) in cs)


def test_broken_link_makes_request_unsatisfiable():
    mesh = MeshSpec(dims=(2, 1, 1), host_block=(1, 1, 1))
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0))}
    # the only 2-chip box spans the dead link
    assert find_slice(mesh, [], count=2, broken=broken) is None
    # single chips are unaffected
    assert find_slice(mesh, [], count=1, broken=broken) is not None


def test_broken_link_only_blocks_boxes_containing_both_ends():
    mesh = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1))
    broken = {(TopologyCoord(1, 0, 0), TopologyCoord(2, 0, 0))}
    coords = find_slice(mesh, [], count=2, broken=broken)
    assert coords is not None
    cs = set(coords)
    assert cs in ({TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)},
                  {TopologyCoord(2, 0, 0), TopologyCoord(3, 0, 0)})


def test_irregular_growth_never_crosses_broken_link():
    mesh = MeshSpec(dims=(3, 1, 1), host_block=(1, 1, 1))
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0))}
    # no 3-box exists without the dead link; irregular growth must not
    # pretend chips 0..2 are connected through it either
    got = find_slice(mesh, [], count=3, allow_irregular=True, broken=broken)
    assert got is None
    # 2 chips connected through the live link still work
    got2 = find_slice(mesh, [], count=2, allow_irregular=True, broken=broken)
    assert got2 is not None
    assert set(got2) == {TopologyCoord(1, 0, 0), TopologyCoord(2, 0, 0)}


def test_broken_link_respected_on_torus_wrap():
    mesh = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1),
                    torus=(True, False, False))
    # wrap link 3-0 is down; a wrapped 2-box {3,0} must be rejected,
    # the interior 2-boxes must not be
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(3, 0, 0))}
    occupied = [TopologyCoord(1, 0, 0), TopologyCoord(2, 0, 0)]
    assert find_slice(mesh, occupied, count=2, broken=broken) is None
    assert find_slice(mesh, [TopologyCoord(2, 0, 0)], count=2,
                      broken=broken) is not None


def test_iter_free_boxes_excludes_broken():
    mesh = MeshSpec(dims=(2, 2, 1), host_block=(1, 1, 1))
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0))}
    grid = occupancy_grid(mesh, [])
    boxes = list(iter_free_boxes(mesh, grid, count=2, broken=broken))
    for sb in boxes:
        cs = set(sb.box.coords())
        assert not (TopologyCoord(0, 0, 0) in cs and TopologyCoord(1, 0, 0) in cs)
    assert boxes  # vertical pairs remain


def test_irregular_region_never_contains_both_ends_of_dead_link():
    # Both endpoints reachable through LIVE paths (around the square) —
    # the region must still not contain both ends of the dead link
    mesh = MeshSpec(dims=(2, 2, 1), host_block=(1, 1, 1))
    broken = {(TopologyCoord(0, 0, 0), TopologyCoord(0, 1, 0))}
    got = find_slice(mesh, [], count=4, allow_irregular=True, broken=broken)
    assert got is None
    got3 = find_slice(mesh, [], count=3, allow_irregular=True, broken=broken)
    assert got3 is not None
    cs = set(got3)
    assert not (TopologyCoord(0, 0, 0) in cs and TopologyCoord(0, 1, 0) in cs)


def test_contact_grid_matches_contact_point():
    from tpukube.sched.slicefit import _Sweep

    rng = random.Random(7)
    for dims, torus in [
        ((4, 4, 4), (False, False, False)),
        ((4, 4, 1), (True, False, False)),
        ((2, 3, 1), (True, True, True)),
        ((1, 4, 2), (False, True, False)),
    ]:
        mesh = MeshSpec(dims=dims, host_block=(1, 1, 1), torus=torus)
        coords = list(mesh.all_coords())
        occupied = rng.sample(coords, k=len(coords) // 3)
        grid = occupancy_grid(mesh, occupied)
        sweep = _Sweep(mesh, grid)
        cg = sweep.contact_grid()
        for c in coords:
            assert int(cg[c]) == sweep.contact_point(c), (dims, torus, c)

"""Regression tests for the round-5 advisor findings (ISSUE 1 satellites):
terminating chips blocked from preemption, queued-victim DELETED
confirmation, stream-connected watch liveness, and the restored
no-chips-in-slice filter message."""

import time as _time
from collections import deque
from types import SimpleNamespace

from tpukube import apiserver as apisrv
from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import AllocResult, PodGroup, TopologyCoord
from tpukube.sched import kube
from tpukube.sched.extender import Extender
from tpukube.sched.gang import GangError
from tpukube.sim import SimCluster


def _mini_extender(dims="2,2,1", block="2,2,1"):
    """Extender over one simulated node (no HTTP), node ingested."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": dims,
        "TPUKUBE_SIM_HOST_BLOCK": block,
    })
    c = SimCluster(cfg)  # never started: only mints node objects
    ext = Extender(cfg)
    for obj in c.node_objects():
        ext.state.upsert_node(obj["metadata"]["name"],
                              obj["metadata"]["annotations"])
    return ext, cfg


def _gang_pod_obj(name, group, tpu=1, namespace="default", priority=100):
    return {
        "metadata": {
            "name": name, "namespace": namespace, "uid": f"uid-{name}",
            "annotations": dict(codec.pod_group_annotations(group)),
        },
        "spec": {
            "priority": priority,
            "containers": [{"name": "main", "resources": {
                "requests": {"qiniu.com/tpu": str(tpu)},
            }}],
        },
    }


def test_preemption_plan_blocked_by_terminating_chips():
    """ADVICE round 5 medium: after a rollback leaves evicted-but-still-
    terminating members' chips ledger-free and reservation-free, a new
    gang planning preemption must NOT open a box over them — those chips
    cannot be freed by evicting anyone."""
    ext, cfg = _mini_extender()  # 4 chips, one host
    sid = cfg.slice_id
    # two chips are physically held by terminating (already-evicted)
    # members of a rolled-back gang: masked, but owned by no workload
    ext.gang._terminating_coords["default/dead-0"] = (
        sid, frozenset({TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)})
    )
    # the other two chips run a cheap evictable pod
    ext.state.commit(AllocResult(
        pod_key="default/cheap", node_name="host-0-0-0",
        device_ids=["tpu-2", "tpu-3"],
        coords=[TopologyCoord(0, 1, 0), TopologyCoord(1, 1, 0)],
        priority=0,
    ))
    group = PodGroup("vip", min_member=4)
    body = {"Pod": _gang_pod_obj("vip-0", group),
            "NodeNames": ["host-0-0-0"]}
    # without the fix: the planner sees the 2 terminating chips as free,
    # evicts only default/cheap, and reserves the whole mesh — binding
    # members onto chips dying containers still hold. With it: no 4-chip
    # box avoids the terminating chips, so preemption must fail loudly.
    res = ext.handle("filter", body)
    assert res["NodeNames"] == []
    assert "no victim set opens" in res["Error"]
    assert ext.gang.reservation("default", "vip") is None
    assert not ext.pending_evictions
    assert ext.state.allocation("default/cheap") is not None


def test_reserve_exact_split_rejects_terminating_chips():
    """The second half of the double-ownership window: even a plan made
    elsewhere cannot reserve chips a terminating victim still holds."""
    ext, cfg = _mini_extender()
    sid = cfg.slice_id
    ext.gang._terminating_coords["default/dead"] = (
        sid, frozenset({TopologyCoord(0, 0, 0)})
    )
    group = PodGroup("g", min_member=2)
    pod = kube.pod_from_k8s(_gang_pod_obj("g-0", group))
    try:
        ext.gang.reserve_exact_split(
            pod, 1,
            {sid: [TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)]},
        )
        assert False, "reservation over terminating chips must clash"
    except GangError as e:
        assert "re-occupied" in str(e)
    # the accessor the preemption planner's blocked set consumes
    assert ext.gang.terminating_coords(sid) == {TopologyCoord(0, 0, 0)}
    assert ext.gang.terminating_coords("other-slice") == set()


def test_confirm_deleted_covers_queued_evictions():
    """ADVICE round 5 low (apiserver:1649): a victim whose DELETED event
    arrives while its key still sits queued on pending_evictions is
    trackable — confirmed immediately (victim_gone fires), and the later
    drain skips the moot eviction instead of re-tracking a deletion the
    watch will never re-deliver (which gated gangs ~30s)."""
    gone: list[str] = []

    class ExtStub(SimpleNamespace):
        def handle(self, kind, body):
            assert kind == "victim_gone"
            gone.append(body["pod_key"])
            return {"cleared": True}

    class RecordingApi:
        def __init__(self):
            self.evict_calls: list[str] = []

        def evict_pod(self, namespace, name, dry_run=False):
            self.evict_calls.append(f"{namespace}/{name}")
            return True  # accepted (or 404: already gone)

        def get_pod(self, namespace, name):
            return None

    api = RecordingApi()
    ext = ExtStub(pending_evictions=deque(["default/v"]))
    execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
    # the lifecycle watch sees DELETED before drain ever ran: the key
    # leaves the queue IMMEDIATELY (a lingering marker would cancel a
    # later legitimate eviction of a reused pod name, and depth would
    # overcount an already-confirmed pod)
    assert execu.confirm_deleted("default/v") is True
    assert gone == ["default/v"]
    assert execu.evicted == 1
    assert list(ext.pending_evictions) == []
    assert execu.depth() == 0
    # drain has nothing to do: no POST, no tracking, no requeue
    assert execu.drain() == []
    assert api.evict_calls == []
    assert execu.evicted == 1  # not double-counted
    # a SAME-NAME victim queued later still gets its eviction POSTed —
    # nothing stale cancels the new incarnation's eviction
    ext.pending_evictions.append("default/v")
    execu.drain()
    assert api.evict_calls == ["default/v"]
    # an unknown key is still untracked
    assert execu.confirm_deleted("default/unknown") is False


def test_drain_after_lost_confirm_race_does_not_leak_age_entry():
    """The queued-victim confirm can lose the race to drain's popleft:
    confirm_deleted's membership check passes, its remove() raises
    ValueError, and its _confirmed() bookkeeping runs BEFORE drain
    re-inserts the key into _pending_since. drain's confirmed-early
    branch must then drop the age entry itself — an orphan would inflate
    tpukube_eviction_oldest_age_seconds forever (a phantom PDB-wedged
    eviction alarm) while depth reads 0."""

    class RaceLostDeque(deque):
        # popleft (drain, other thread) wins between confirm_deleted's
        # membership check and its remove()
        def remove(self, value):
            raise ValueError(value)

    class ExtStub(SimpleNamespace):
        def handle(self, kind, body):
            return {"cleared": True}

    class Api:
        def evict_pod(self, namespace, name, dry_run=False):
            return True

        def get_pod(self, namespace, name):
            return None

    ext = ExtStub(pending_evictions=RaceLostDeque(["default/v"]))
    execu = apisrv.EvictionExecutor(ext, Api(), poll_seconds=999)
    assert execu.confirm_deleted("default/v") is True  # ValueError path
    execu.drain()  # popleft + POST; sees _confirmed_early
    assert execu._pending_since == {}
    assert execu.oldest_age_seconds() == 0.0
    assert execu.depth() == 0


def test_watch_alive_requires_connected_stream():
    """ADVICE round 5 low (apiserver:1089): watch_alive() must require a
    currently-connected stream, not merely a live thread — during
    reconnect backoff the executor must GET-confirm immediately instead
    of deferring 30s on the strength of a dead stream."""
    api = apisrv.FakeApiServer()
    ext = SimpleNamespace(
        pending_evictions=deque(),
        state=SimpleNamespace(allocation=lambda key: None,
                              allocations=lambda: []),
        handle=lambda kind, body: {"cleared": True},
    )
    loop = apisrv.PodLifecycleReleaseLoop(ext, api, poll_seconds=999)
    assert loop._use_watch
    assert not loop.watch_alive()          # not started: no stream
    loop.start()
    try:
        deadline = _time.monotonic() + 5
        while not loop.stream_connected() and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert loop.stream_connected()
        assert loop.watch_alive()
        status = loop.watch_status()
        assert status["stream_connected"] is True
        assert status["thread_alive"] is True
        assert isinstance(status["last_event_ts"], float)
        # simulate the reconnect-backoff window: thread alive, stream not
        loop._stream_connected = False
        assert loop._thread.is_alive()
        assert not loop.watch_alive()
        loop._stream_connected = True      # restore for clean shutdown
    finally:
        loop.stop()
    assert not loop.watch_alive()


def test_watch_alive_consults_informer_host_stream():
    """Under a shared PodInformer, the child's watch_alive() follows the
    INFORMER's stream state."""
    api = apisrv.FakeApiServer()
    ext = SimpleNamespace(
        pending_evictions=deque(),
        state=SimpleNamespace(allocation=lambda key: None,
                              allocations=lambda: []),
        handle=lambda kind, body: {"cleared": True},
    )
    lifecycle = apisrv.PodLifecycleReleaseLoop(ext, api, poll_seconds=999)
    informer = apisrv.PodInformer(api, [lifecycle], poll_seconds=999)
    assert not lifecycle.watch_alive()
    informer.start()
    try:
        deadline = _time.monotonic() + 5
        while (not informer.stream_connected()
               and _time.monotonic() < deadline):
            _time.sleep(0.01)
        assert lifecycle.watch_alive()
        informer._stream_connected = False
        assert not lifecycle.watch_alive()
        informer._stream_connected = True
    finally:
        informer.stop()
    assert not lifecycle.watch_alive()


def test_gang_filter_message_distinguishes_foreign_slice():
    """ADVICE round 5 low (gang:781): a node whose ICI slice holds none
    of the reservation's chips fails with the historical 'gang holds no
    chips in this node's ICI slice', while an in-slice node that merely
    hosts none of the reserved coords keeps the counted message."""
    cfg = load_config(env={"TPUKUBE_SIM_HOST_BLOCK": "2,2,1"})
    spec = MeshSpec(dims=(4, 2, 1), host_block=(2, 2, 1))
    c = SimCluster(cfg, slices={"sa": spec, "sb": spec})
    ext = Extender(cfg)
    for obj in c.node_objects():
        ext.state.upsert_node(obj["metadata"]["name"],
                              obj["metadata"]["annotations"])
    group = PodGroup("g", min_member=2)
    pod = kube.pod_from_k8s(_gang_pod_obj("g-0", group, tpu=2, priority=0))
    res = ext.gang.ensure_reservation(pod, 2)
    assert res.slice_id == "sa"  # deterministic tie-break on slice id
    counts = ext.gang.node_availability(res)
    # foreign slice: the restored historical message
    assert ext.gang.feasibility_from(counts, res, "sb-host-0-0-0") == \
        "gang holds no chips in this node's ICI slice"
    # in-slice node hosting none of the reserved coords: counted message
    in_slice_empty = [
        n for n in ("sa-host-0-0-0", "sa-host-1-0-0")
        if n not in counts
    ]
    assert in_slice_empty, "expected one sa host outside the reserved box"
    assert ext.gang.feasibility_from(counts, res, in_slice_empty[0]) == \
        "gang slice has 0 unassigned chips here, pod needs 2"
    # a hosting node with room: feasible
    hosting = next(iter(counts))
    assert ext.gang.feasibility_from(counts, res, hosting) is None

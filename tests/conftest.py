"""Test bootstrap: force a virtual 8-device CPU mesh BEFORE jax imports.

Multi-chip hardware is unavailable here; sharding paths are validated on a
virtual CPU mesh exactly as the driver's dryrun does (task brief).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Test bootstrap: force a virtual 8-device CPU mesh for sharding tests.

Multi-chip hardware is unavailable here; sharding paths are validated on a
virtual CPU mesh exactly as the driver's dryrun does (task brief).

This machine's interpreter imports jax at startup (an axon/TPU sitecustomize
registers a PJRT plugin and JAX_PLATFORMS=axon is pre-set in the env), so
setting os.environ here is too late for platform selection — use
jax.config.update instead, plus XLA_FLAGS before any backend initializes.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses spawned by tests

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Prometheus text-format lint over both daemons' live /metrics
(satellite of ISSUE 2): every future series addition must keep a TYPE
line per family, unique series, and parseable label escaping — this
scrapes the REAL endpoints, so a bad series fails here before any
dashboard sees it."""

import urllib.request

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.obs.slo import parse_metrics, validate_exposition
from tpukube.sim import SimCluster


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


def test_extender_metrics_endpoint_lints_clean():
    """The extender's /metrics after real activity — binds, a gang, a
    preemption, faults — must parse and lint clean."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"low-{i}", tpu=2, priority=0))
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=2, priority=100,
                                  group=group))
        c.inject_fault("host-0-0-0", 0)
        text = _scrape(f"{c.base_url}/metrics")
    errors = validate_exposition(text)
    assert errors == [], "\n".join(errors)
    # and it is substantive: both histogram families + counters present
    names = {s.name for s in parse_metrics(text)}
    assert "gang_schedule_latency_seconds_bucket" in names
    assert "tpukube_webhook_latency_seconds_bucket" in names
    assert "tpukube_events_total" in names


def test_node_agent_metrics_endpoint_lints_clean(tmp_path):
    """The node agent's MetricsServer /metrics with the full
    observability surface attached (telemetry sampler, journal, health
    watcher) and label-hostile state (a fault, a weird intent key)."""
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import MetricsServer, render_plugin_metrics
    from tpukube.obs.events import EventJournal
    from tpukube.obs.health import HealthSampler
    from tpukube.plugin import DevicePluginServer, HealthWatcher

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        journal = EventJournal()
        server.events = journal
        sampler = HealthSampler(device, journal=journal, poll_seconds=999)
        watcher = HealthWatcher(device, server, poll_seconds=999)
        sampler.check_once()
        device.inject_fault(0)
        sampler.check_once()
        watcher.check_once()
        ms = MetricsServer(lambda: render_plugin_metrics(
            server, health=watcher, sampler=sampler, events=journal,
        ))
        ms.start()
        try:
            text = _scrape(f"http://127.0.0.1:{ms.port}/metrics")
        finally:
            ms.stop()
    errors = validate_exposition(text)
    assert errors == [], "\n".join(errors)
    names = {s.name for s in parse_metrics(text)}
    assert "tpukube_chip_healthy" in names
    assert "tpukube_chip_ici_link_errors_total" in names
    assert "tpukube_plugin_devices" in names

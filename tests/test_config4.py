"""BASELINE config 4: 16-pod gang-scheduled JAX Llama-3-8B job with
ICI-contiguous slice binding, on a simulated multi-host v5p-style mesh."""

import pytest

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster


def test_config4_sixteen_pod_gang_contiguous():
    # 4x4x4 mesh = 64 chips over 16 hosts (2x2x1 blocks) with some
    # pre-existing load; the 16-pod gang must land as one contiguous box
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,4",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        # background load: 8 chips of non-gang pods
        for i in range(2):
            c.schedule(c.make_pod(f"bg-{i}", tpu=4))

        group = PodGroup("llama-8b", min_member=16)
        allocs = []
        for i in range(16):
            node, alloc = c.schedule(
                c.make_pod(f"llama-8b-{i}", tpu=1, group=group)
            )
            allocs.append(alloc)

        res = c.extender.gang.reservation("default", "llama-8b")
        assert res.committed
        assert res.commit_latency is not None

        coords = sorted(co for a in allocs for co in a.coords)
        assert len(set(coords)) == 16
        # ICI-contiguity: the 16 chips form an axis-aligned box
        xs = sorted({c_[0] for c_ in coords})
        ys = sorted({c_[1] for c_ in coords})
        zs = sorted({c_[2] for c_ in coords})
        assert len(xs) * len(ys) * len(zs) == 16
        assert xs == list(range(xs[0], xs[0] + len(xs)))
        assert ys == list(range(ys[0], ys[0] + len(ys)))
        assert zs == list(range(zs[0], zs[0] + len(zs)))

        # all-or-nothing held: utilization = background + gang
        assert c.utilization() == pytest.approx((8 + 16) / 64)

        # each member's Allocate works through the real plugin stack and
        # exports its global coords for the in-pod JAX mesh
        env = c.execute_allocation(allocs[0])
        assert env["TPU_KUBE_MESH_DIMS"] == "4,4,4"


def test_config4_partial_gang_never_occupies():
    # only 10 of 16 members show up -> TTL rollback -> zero residue
    import time
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,4",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_RESERVATION_TTL_SECONDS": "0.3",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("half", min_member=16)
        for i in range(10):
            c.schedule(c.make_pod(f"h-{i}", tpu=1, group=group))
        time.sleep(0.4)
        c.extender.gang.sweep()
        assert c.utilization() == 0.0
        assert c.extender.gang.reservation("default", "half") is None

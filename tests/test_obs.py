"""Unified observability layer (ISSUE 1 tentpole): metrics registry,
per-pod timelines, /statusz introspection."""

import json
import urllib.error
import urllib.request

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    escape_label_value,
    format_sample,
)
from tpukube.sim import SimCluster


# -- registry ----------------------------------------------------------------

def test_registry_counter_gauge_labels_and_order():
    reg = Registry()
    c = reg.counter("reqs_total")
    c.labels(code="200").inc()
    c.labels(code="500").inc(2)
    g = reg.gauge("depth")
    g.set(3)
    text = reg.render()
    assert text == (
        "# TYPE reqs_total counter\n"
        'reqs_total{code="200"} 1\n'
        'reqs_total{code="500"} 2\n'
        "# TYPE depth gauge\n"
        "depth 3\n"
    )
    # children render in creation order; counters refuse set()
    try:
        c.set(7)
        assert False, "Counter.set must raise"
    except TypeError:
        pass


def test_registry_label_escaping():
    """Arbitrary runtime text in label values (inventory_source carries
    PJRT error strings) must not corrupt the exposition format."""
    line = format_sample("m", 1, {"source": 'table (err "quoted"\nline\\x)'})
    assert line == 'm{source="table (err \\"quoted\\"\\nline\\\\x)"} 1\n'
    assert escape_label_value('a"b\nc\\d') == 'a\\"b\\nc\\\\d'
    # legacy import surface kept alive
    from tpukube.metrics import _fmt

    assert _fmt is format_sample


def test_registry_duplicate_name_rejected():
    reg = Registry()
    reg.counter("x_total")
    try:
        reg.counter("x_total")
        assert False, "duplicate family must raise"
    except ValueError:
        pass
    # a histogram PAIRED with a summary of the same family is the one
    # sanctioned overlap (the legacy gang series)
    reg.summary("lat_seconds")
    reg.histogram("lat_seconds", bucket_only=True)


def test_histogram_bucket_boundaries():
    """le is inclusive: an observation exactly on a boundary lands in
    that bucket; the +Inf terminal bucket counts everything."""
    h = Histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.0100001, 0.1, 5.0):
        h.observe(v)
    assert h.bucket_counts([0.005, 0.01, 0.0100001, 0.1, 5.0]) == [2, 4, 4, 5]
    text = h.render()
    assert '# TYPE lat histogram' in text
    assert 'lat_bucket{le="0.01"} 2\n' in text
    assert 'lat_bucket{le="0.1"} 4\n' in text
    assert 'lat_bucket{le="1"} 4\n' in text
    assert 'lat_bucket{le="+Inf"} 5\n' in text
    assert 'lat_count 5\n' in text
    assert 'lat_sum 5.12' in text


def test_histogram_buckets_are_monotonic_counters():
    """_bucket series are Prometheus counters: cumulative since process
    start, never a window snapshot. The extender's latency deques are
    bounded (maxlen eviction) and gang rollback REMOVES a sample — a
    bucket count derived from either would decrease between scrapes and
    Prometheus would read the dip as a counter reset, garbaging every
    rate()/histogram_quantile() over the series."""
    from tpukube.sched.extender import Extender

    h = Histogram("lat_seconds", buckets=(1.0,), bucket_only=True)
    h.observe(0.5)
    h.observe(0.5)
    assert 'lat_seconds_bucket{le="1"} 2\n' in h.render()
    # Histogram is observation-only by design: a pull callback over a
    # sliding window cannot be monotonic
    try:
        Histogram("x", values_fn=lambda: [1.0])
        assert False, "Histogram must not accept values_fn"
    except TypeError:
        pass

    # the daemon wiring: undoing a gang commit removes the summary's
    # windowed sample but the bucket counters keep theirs
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    ext = Extender(cfg)
    ext.gang.commit_latencies.append(0.07)
    ext.gang.commit_hist.observe(0.07)
    from tpukube.metrics import render_extender_metrics

    before = render_extender_metrics(ext)
    assert 'gang_schedule_latency_seconds_bucket{le="+Inf"} 1\n' in before
    ext.gang.commit_latencies.remove(0.07)  # what undo_commit does
    after = render_extender_metrics(ext)
    assert "gang_schedule_latency_seconds_count 0\n" in after
    assert 'gang_schedule_latency_seconds_bucket{le="+Inf"} 1\n' in after


def test_summary_quantiles_and_count_sum():
    s = Summary("q_seconds", quantiles=(0.5, 0.99))
    for v in (1.0, 2.0, 3.0):
        s.observe(v)
    text = s.render()
    assert 'q_seconds{quantile="0.5"} 2\n' in text
    assert 'q_seconds{quantile="0.99"} 3\n' in text
    assert "q_seconds_count 3\n" in text
    assert "q_seconds_sum 6\n" in text


# -- byte-compat golden files ------------------------------------------------

EXTENDER_GOLDEN = """\
# TYPE tpu_chip_utilization_percent gauge
tpu_chip_utilization_percent 0
# TYPE gang_schedule_latency_seconds summary
gang_schedule_latency_seconds{quantile="0.5"} 0.01
gang_schedule_latency_seconds{quantile="0.9"} 0.3
gang_schedule_latency_seconds{quantile="0.99"} 0.3
gang_schedule_latency_seconds_count 2
gang_schedule_latency_seconds_sum 0.31
# TYPE tpukube_ici_links_down gauge
tpukube_ici_links_down 0
# TYPE tpukube_binds_total counter
tpukube_binds_total 0
# TYPE tpukube_gang_rollbacks_total counter
tpukube_gang_rollbacks_total 0
# TYPE tpukube_preemptions_total counter
tpukube_preemptions_total 0
# TYPE tpukube_webhook_latency_seconds summary
tpukube_webhook_latency_seconds{handler="filter",quantile="0.5"} 0.001
tpukube_webhook_latency_seconds{handler="filter",quantile="0.99"} 0.002
tpukube_webhook_latency_seconds{handler="prioritize",quantile="0.5"} 0
tpukube_webhook_latency_seconds{handler="prioritize",quantile="0.99"} 0
tpukube_webhook_latency_seconds{handler="bind",quantile="0.5"} 0.5
tpukube_webhook_latency_seconds{handler="bind",quantile="0.99"} 0.5
# TYPE tpukube_gang_victims_terminating gauge
tpukube_gang_victims_terminating 0
# TYPE tpukube_evictions_pending gauge
tpukube_evictions_pending 1
"""


def test_extender_metrics_byte_compat_golden():
    """The registry refactor must render every legacy series
    byte-identically (golden captured from the pre-registry renderer);
    the histogram ``_bucket`` families are the only additions."""
    from tpukube.metrics import render_extender_metrics
    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    ext = Extender(cfg)
    # through the daemon's real recording surfaces, so the windowed
    # summaries AND the cumulative bucket counters both fill
    ext._observe_latency("filter", 0.001)
    ext._observe_latency("filter", 0.002)
    ext._observe_latency("bind", 0.5)
    for v in (0.01, 0.3):
        ext.gang.commit_latencies.append(v)
        ext.gang.commit_hist.observe(v)
    ext.pending_evictions.append("default/x")
    text = render_extender_metrics(ext)
    # additions since the golden was captured: the _bucket histogram
    # families (PR 1), the event-journal counter (PR 2, which also
    # opts into # HELP), the scheduling-snapshot cache + per-slice
    # fragmentation families (ISSUE 5), and the bulk-ingest families
    # (ISSUE 15, default-on like the snapshot deltas). Everything else
    # must render byte-identically.
    legacy = "".join(
        line for line in text.splitlines(keepends=True)
        if "_bucket" not in line
        and "tpukube_events_total" not in line
        and "tpukube_snapshot_" not in line
        and "tpukube_slice_" not in line
        and "tpukube_ingest_" not in line
        and not line.startswith("# HELP")
    )
    assert legacy == EXTENDER_GOLDEN
    # ...and the additions are real histogram series
    assert 'gang_schedule_latency_seconds_bucket{le="0.01"} 1\n' in text
    assert 'gang_schedule_latency_seconds_bucket{le="+Inf"} 2\n' in text
    assert ('tpukube_webhook_latency_seconds_bucket'
            '{handler="bind",le="0.5"} 1\n') in text
    assert ('tpukube_webhook_latency_seconds_bucket'
            '{handler="prioritize",le="+Inf"} 0\n') in text


PLUGIN_GOLDEN = """\
# TYPE tpukube_plugin_allocations_total counter
tpukube_plugin_allocations_total 0
# TYPE tpukube_plugin_devices gauge
tpukube_plugin_devices{health="Healthy"} 4
tpukube_plugin_devices{health="Unhealthy"} 0
tpukube_plugin_resource_info{resource="qiniu.com/tpu"} 1
# TYPE tpukube_plugin_inventory_source gauge
tpukube_plugin_inventory_source{source="sim"} 1
# TYPE tpukube_plugin_intent_depth gauge
tpukube_plugin_intent_depth 0
# TYPE tpukube_plugin_divergences_total counter
tpukube_plugin_divergences_total 0
"""


def test_plugin_metrics_byte_compat_golden(tmp_path):
    """Node-agent renderer: byte-identical to the pre-registry output,
    including the quirk that resource_info rides without its own TYPE."""
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import render_plugin_metrics
    from tpukube.plugin import DevicePluginServer

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        assert render_plugin_metrics(server) == PLUGIN_GOLDEN


# -- per-pod timelines -------------------------------------------------------

def _gang16_cluster_with_trace():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    return SimCluster(cfg)


def test_timeline_span_chain_for_16_pod_gang(tmp_path):
    """Acceptance: a trace captured from the 16-pod gang config exports
    valid Chrome trace-event JSON with one complete span chain
    (filter -> gang_reserve -> bind -> allocate) per pod."""
    from tpukube.obs import timeline

    with _gang16_cluster_with_trace() as c:
        group = PodGroup("llama-8b", min_member=16)
        allocs = []
        for i in range(16):
            _, alloc = c.schedule(
                c.make_pod(f"llama-8b-{i}", tpu=1, priority=10, group=group)
            )
            allocs.append(alloc)
        # the node-agent leg: a real device-plugin Allocate per pod,
        # span-sinked into the extender's trace
        for alloc in allocs:
            c.execute_allocation(alloc)
        events = c.extender.trace.events()

    chains = timeline.span_chains(events)
    for i in range(16):
        # one complete span chain per pod:
        # filter -> gang_reserve -> bind -> allocate
        chain = chains[f"default/llama-8b-{i}"]
        assert "filter" in chain
        assert "gang_reserve" in chain
        assert "bind" in chain
        assert chain.index("bind") > chain.index("filter")
        assert chain.index("allocate") > chain.index("bind")
        # the kubelet chose exactly the planned chips
        assert "intent_match" in chain
    # exactly one gang_commit span, on the quorum member's track
    assert sum(chain.count("gang_commit")
               for chain in chains.values()) == 1

    # valid Chrome trace-event JSON (Perfetto's object format)
    doc = timeline.chrome_trace(events)
    blob = json.dumps(doc)
    parsed = json.loads(blob)
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for ev in parsed["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
            assert isinstance(ev["name"], str)

    # phase stats cover the chain phases (the bench line's new key); a
    # pod's FIRST event has an undefined width — counted, but excluded
    # from the percentiles (null when a phase was only ever first)
    stats = timeline.phase_stats(events)
    for phase in ("filter", "gang_reserve", "bind", "allocate"):
        assert stats[phase]["count"] >= 1
        p50 = stats[phase]["p50_ms"]
        assert p50 is None or p50 >= 0
    # bind/allocate always follow earlier events on the pod's track, so
    # their widths are defined and must be real measurements
    assert stats["bind"]["p50_ms"] is not None
    assert stats["allocate"]["p50_ms"] is not None


def test_timeline_cli_roundtrip(tmp_path, capsys):
    """``tpukube obs timeline <trace.jsonl>`` writes loadable JSON."""
    from tpukube import cli

    trace_file = tmp_path / "trace.jsonl"
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_TRACE_PATH": str(trace_file),
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=1))
    out_file = tmp_path / "chrome.json"
    rc = cli.main_obs(["timeline", str(trace_file), "-o", str(out_file)])
    assert rc == 0
    doc = json.loads(out_file.read_text())
    names = {ev["name"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
    assert {"filter", "prioritize", "bind"} <= names
    # stdout mode + --stats
    rc = cli.main_obs(["timeline", str(trace_file), "--stats"])
    assert rc == 0
    captured = capsys.readouterr()
    assert json.loads(captured.out)["traceEvents"]
    assert "bind" in json.loads(captured.err)


def test_span_events_do_not_break_replay(tmp_path):
    """A capture with span annotations still replays clean — spans are
    observability markers, not decisions."""
    from tpukube import trace as trace_mod

    trace_file = tmp_path / "trace.jsonl"
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_TRACE_PATH": str(trace_file),
    })
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
    events = trace_mod.load(str(trace_file))
    assert any(e["kind"] == "span" for e in events)
    assert trace_mod.replay(events) == []


# -- /statusz ----------------------------------------------------------------

def test_extender_statusz_endpoint():
    """/statusz on the extender app: ledger/gang summary, pending
    evictions with ages, watch liveness with a LAST-EVENT timestamp
    (connected stream, not just live thread), trace-ring stats."""
    import time as _time

    from tpukube.apiserver import (
        EvictionExecutor,
        FakeApiServer,
        PodInformer,
        PodLifecycleReleaseLoop,
    )
    from tpukube.sched.extender import make_app
    from tpukube.sim.harness import _AppThread, _free_port

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p", tpu=2))
        api = FakeApiServer()
        # mirror the bound pod into the fake apiserver so the informer's
        # startup resync finds the allocation's pod object alive
        api.upsert_pod(c.pods["default/p"])
        evictions = EvictionExecutor(c.extender, api, poll_seconds=999)
        lifecycle = PodLifecycleReleaseLoop(
            c.extender, api, poll_seconds=999, evictions=evictions,
        )
        informer = PodInformer(api, [lifecycle], poll_seconds=999)
        informer.start()
        try:
            deadline = _time.monotonic() + 5
            while (not informer.stream_connected()
                   and _time.monotonic() < deadline):
                _time.sleep(0.01)
            port = _free_port()
            app = _AppThread(
                make_app(c.extender, evictions=evictions,
                         lifecycle=lifecycle, informer=informer),
                "127.0.0.1", port,
            )
            app.start()
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/statusz", timeout=5
                ) as r:
                    doc = json.loads(r.read())
            finally:
                app.stop()
        finally:
            informer.stop()

    assert doc["component"] == "extender"
    assert doc["ledger"]["allocations"] == 1
    assert doc["ledger"]["utilization_percent"] == 50.0
    assert doc["gangs"]["reservations"] == 0
    assert doc["pending_evictions"]["depth"] == 0
    assert doc["trace"]["enabled"] and doc["trace"]["last_seq"] >= 3
    watch = doc["pod_watch"]
    assert watch["configured"] and watch["mode"] == "watch"
    assert watch["stream_connected"] is True
    assert isinstance(watch["last_event_ts"], float)


def test_extender_statusz_reports_pending_evictions_with_ages():
    from tpukube.obs.statusz import extender_statusz

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        c.extender.pending_evictions.append("default/victim")
        doc = extender_statusz(c.extender)
        assert doc["pending_evictions"]["depth"] == 1
        entry = doc["pending_evictions"]["entries"][0]
        assert entry["pod"] == "default/victim"
        assert entry["state"] == "queued"
        c.extender.pending_evictions.clear()


def test_plugin_statusz_endpoint(tmp_path):
    """/statusz on the node agent's MetricsServer: devices, inventory
    source, intents, watch liveness."""
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import MetricsServer
    from tpukube.obs.statusz import plugin_statusz
    from tpukube.plugin import DevicePluginServer

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with TpuDeviceManager(cfg) as device, \
            DevicePluginServer(cfg, device) as server:
        server.intents.put("default/p0", ["tpu-0"])
        ms = MetricsServer(
            lambda: "",
            statusz=lambda: plugin_statusz(server, device=device),
        )
        ms.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/statusz", timeout=5
            ) as r:
                doc = json.loads(r.read())
        finally:
            ms.stop()
    assert doc["component"] == "plugin"
    assert doc["resource"] == "qiniu.com/tpu"
    assert doc["devices"] == {"healthy": 4, "unhealthy": 0}
    assert doc["inventory_source"] == "sim"
    assert doc["intents"] == {"depth": 1, "pending": ["default/p0"]}
    assert doc["intent_watch"] == {"configured": False}


def test_metrics_server_without_statusz_404s(tmp_path):
    from tpukube.metrics import MetricsServer

    ms = MetricsServer(lambda: "x 1\n")
    ms.start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/statusz", timeout=5
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        ms.stop()


def test_bench_line_gains_phase_stats():
    """Scenario 5 (the bench.py headline) now carries per-phase timeline
    stats under the NEW ``phases`` key; every pre-existing key parses
    unchanged."""
    from tpukube.sim import scenarios

    result = scenarios.run(5, None)
    for key in ("metric", "value", "unit", "vs_baseline", "gang_p50_s",
                "preemptions", "pods_placed"):
        assert key in result
    phases = result["phases"]
    assert phases["filter"]["count"] > 0
    assert phases["bind"]["count"] > 0
    assert set(phases["bind"]) == {"count", "p50_ms", "p99_ms", "max_ms"}
    json.dumps(result)  # still one JSON-able line


def test_registry_help_lines_opt_in():
    """Satellite: # HELP is opt-in per family — new telemetry/event
    series carry it, legacy families stay HELP-free (byte-compat
    goldens above prove the latter)."""
    from tpukube.obs.registry import Registry

    reg = Registry()
    reg.counter("helped_total", help_text='has "quotes" and\nnewline \\x')
    reg.gauge("plain")
    text = reg.render()
    assert ("# HELP helped_total has \"quotes\" and\\nnewline \\\\x\n"
            "# TYPE helped_total counter\n") in text
    assert "# HELP plain" not in text
    # bucket_only histograms HELP their actual family name
    reg2 = Registry()
    reg2.summary("lat_seconds")
    reg2.histogram("lat_seconds", bucket_only=True, help_text="buckets")
    assert "# HELP lat_seconds_bucket buckets\n" in reg2.render()


def test_timeline_tolerates_span_only_pods(tmp_path):
    """Satellite regression: a pod with span annotations but no
    bind/filter decision events (crashed or still-pending) — plus junk
    entries from a torn capture — must not break the timeline
    exporter."""
    import time as _time

    from tpukube import cli
    from tpukube.obs import timeline

    now = _time.time()
    events = [
        # a normal pod with a full chain
        {"seq": 1, "ts": now, "kind": "filter",
         "request": {"Pod": {"metadata": {"name": "ok",
                                          "namespace": "default"}}},
         "response": {"NodeNames": ["n1"], "FailedNodes": {}}},
        {"seq": 2, "ts": now + 0.01, "kind": "bind",
         "request": {"PodName": "ok", "PodNamespace": "default"},
         "response": {}},
        # a crashed pod: spans only, no decisions ever recorded
        {"seq": 3, "ts": now + 0.02, "kind": "span",
         "request": {"name": "gang_reserve", "pod_key": "default/crashed",
                     "gang": "default/g"}, "response": None},
        {"seq": 4, "ts": now + 0.03, "kind": "span",
         "request": {"name": "allocate", "pod_key": "default/crashed",
                     "devices": ["tpu-0"]}, "response": None},
        # junk a torn capture can contain
        "not even a dict",
        {"seq": 5, "kind": "span"},            # no ts
        {"seq": 6, "ts": "corrupt", "kind": "bind"},  # non-numeric ts
        {"seq": 7, "ts": now + 0.04, "kind": "span", "request": None},
    ]
    chains = timeline.span_chains(events)
    assert chains["default/crashed"] == ["gang_reserve", "allocate"]
    assert chains["default/ok"] == ["filter", "bind"]
    doc = timeline.chrome_trace(events)
    assert any(ev.get("name") == "allocate"
               for ev in doc["traceEvents"])
    stats = timeline.phase_stats(events)
    assert stats["gang_reserve"]["count"] == 1
    # the allocate slice's width is measurable (it follows the reserve)
    assert stats["allocate"]["p50_ms"] is not None

    # end to end through the CLI, including a torn final line
    trace_file = tmp_path / "trace.jsonl"
    with open(trace_file, "w") as f:
        for ev in events:
            if isinstance(ev, dict):
                f.write(json.dumps(ev) + "\n")
        f.write('{"seq": 8, "ts": 1.0, "kind": "bi')  # torn
    out_file = tmp_path / "out.json"
    rc = cli.main_obs(["timeline", str(trace_file), "-o", str(out_file)])
    assert rc == 0
    assert json.loads(out_file.read_text())["traceEvents"]


def test_bench_process_stats_and_churn_phases():
    """Satellite: the bench line's new ``process`` key (peak RSS, CPU
    time) and the churn scenario's ``phases`` key."""
    import bench
    from tpukube.sim import scenarios

    proc = bench.process_stats()
    assert proc["peak_rss_bytes"] > 10 * 1024 * 1024
    assert proc["cpu_user_s"] >= 0 and proc["cpu_system_s"] >= 0

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    result = scenarios.churn(cfg)
    phases = result["phases"]
    assert phases["bind"]["count"] > 0
    assert set(phases["bind"]) == {"count", "p50_ms", "p99_ms", "max_ms"}
    json.dumps(result)

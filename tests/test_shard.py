"""ISSUE 13: slice-partitioned control plane — router, two-phase DCN
rendezvous, replica chaos, plan-served filter answers, and the
incremental per-slice occupied sets.

The acceptance gates:
  * N=1 sharded path byte-identical to the unsharded planner (the
    router delegates verbatim — proven end to end on real webhook
    bodies);
  * rendezvous commit / abort-on-timeout / duplicate-prepare
    idempotency;
  * replica kill and partition mid-gang-commit converge via
    rebuild_from_pods with zero reservation leaks (audit green).
"""

from __future__ import annotations

import json

import pytest

from tpukube.chaos import leaked_reservations, ledger_divergence
from tpukube.core import codec
from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sched.shard import ShardRouter
from tpukube.sim.harness import SimCluster


def two_slices() -> dict[str, MeshSpec]:
    return {
        "s0": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                       torus=(False, False, False)),
        "s1": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                       torus=(False, False, False)),
    }


def sharded_config(n: int = 2, **extra: str):
    env = {
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_BATCH_ENABLED": "1",
        **extra,
    }
    return load_config(env=env)


def fill_slices(c: SimCluster) -> None:
    """Commit one 4-member gang into each slice so no slice can hold
    an 8-chip gang whole — the shape that forces a rendezvous."""
    for g in ("fill-a", "fill-b"):
        grp = PodGroup(g, min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"{g}-{i}", tpu=1, group=grp))


def settle(c: SimCluster, rounds: int = 4) -> None:
    for _ in range(rounds):
        c.drain_evictions()
        c._lifecycle.check_once()
        c.extender.sweep()


# -- N=1 parity gate ---------------------------------------------------------

def test_n1_router_is_byte_identical_to_unsharded():
    """Every webhook response from a planner_replicas=1 router equals
    the plain Extender's, byte for byte, over a mixed workload (single
    pods, a gang, a release, node re-sends)."""
    from tpukube.sched.extender import Extender

    cfg = load_config(env={"TPUKUBE_BATCH_ENABLED": "1"})
    mesh = MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                    torus=(False, False, False))
    from tpukube.core.types import ChipInfo, NodeInfo

    def node_objs():
        out = []
        for host in mesh.all_hosts():
            chips = [
                ChipInfo(chip_id=f"{host}-chip-{i}", index=i,
                         coord=coord, hbm_bytes=1 << 30, num_cores=2)
                for i, coord in enumerate(mesh.coords_of_host(host))
            ]
            info = NodeInfo(name=host, chips=chips, shares_per_chip=1,
                            slice_id="slice-0")
            out.append({"metadata": {
                "name": host,
                "annotations": codec.annotate_node(info, mesh),
            }})
        return out

    def pod_obj(name, group=None):
        annotations = {}
        if group is not None:
            annotations.update(codec.pod_group_annotations(group))
        return {
            "metadata": {"name": name, "namespace": "default",
                         "uid": f"uid-{name}",
                         "annotations": annotations, "labels": {}},
            "spec": {"priority": 0, "containers": [
                {"name": "main",
                 "resources": {"requests": {"qiniu.com/tpu": "1"}}},
            ]},
        }

    def drive(target) -> list[str]:
        responses = []
        nodes = node_objs()
        grp = PodGroup("parity-gang", min_member=2)
        workload = [pod_obj("solo-0"), pod_obj("solo-1"),
                    pod_obj("pg-0", grp), pod_obj("pg-1", grp)]
        for pod in workload:
            body = {"Pod": pod, "Nodes": {"Items": nodes}}
            fres = target.handle("filter", body)
            responses.append(json.dumps(fres, sort_keys=True))
            feasible = fres["NodeNames"]
            pres = target.handle("prioritize", {
                "Pod": pod, "NodeNames": feasible,
            })
            responses.append(json.dumps(pres, sort_keys=True))
            scores = {e["Host"]: e["Score"] for e in pres}
            best = max(sorted(scores), key=lambda h: scores[h])
            bres = target.handle("bind", {
                "PodName": pod["metadata"]["name"],
                "PodNamespace": "default",
                "PodUID": pod["metadata"]["uid"],
                "Node": best,
            })
            responses.append(json.dumps(bres, sort_keys=True))
        target.handle("release", {"pod_key": "default/solo-0"})
        responses.append(json.dumps(
            target.gang_snapshot(), sort_keys=True))
        responses.append(json.dumps(
            target.alloc_snapshot(), sort_keys=True))
        return responses

    plain = drive(Extender(cfg))
    routed = drive(ShardRouter(cfg))
    assert plain == routed


def test_router_n1_delegates_to_sole_extender():
    cfg = load_config(env={})
    router = ShardRouter(cfg)
    assert router._sole is router.replicas[0].extender
    # the eviction bus is the sole replica's own deque
    assert router.pending_evictions is \
        router.replicas[0].extender.pending_evictions


# -- two-phase rendezvous ----------------------------------------------------

def test_rendezvous_commit_and_global_env():
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        for i in range(8):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        rz = c.extender.statusz()["rendezvous"]
        assert rz["prepared"] == 1 and rz["committed"] == 1
        assert rz["aborted"] == 0
        live = rz["live"][0]
        assert live["committed"] is True
        assert live["parts"] == {"r0": {"s0": 4}, "r1": {"s1": 4}}
        # both local parts committed by their LOCAL quorum
        parts = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "dcn"]
        assert len(parts) == 2 and all(g["committed"] for g in parts)
        assert sorted(g["min_member"] for g in parts) == [4, 4]
        # the pod annotation's gang env is GLOBALIZED: every member
        # sees the full multislice topology, not just its part
        from tpukube.device.tpu import (
            ENV_GANG_NUM_SLICES,
            ENV_GANG_SLICE_INDEX,
            ENV_GANG_SLICES,
        )

        indices = set()
        for i in range(8):
            pod = c.pods[f"default/dcn-{i}"]
            alloc = codec.decode_alloc(
                pod["metadata"]["annotations"][codec.ANNO_ALLOC]
            )
            assert alloc.env[ENV_GANG_NUM_SLICES] == "2"
            assert alloc.env[ENV_GANG_SLICES] == "s0,s1"
            indices.add(alloc.env[ENV_GANG_SLICE_INDEX])
        assert indices == {"0", "1"}
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


def test_rendezvous_prefers_single_replica_fit():
    """A DCN-capable gang that FITS one replica whole never pays the
    rendezvous — ICI-contiguous placement stays the first choice."""
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        gd = PodGroup("easy", min_member=8, allow_dcn=True)
        for i in range(8):
            c.schedule(c.make_pod(f"easy-{i}", tpu=1, group=gd))
        rz = c.extender.statusz()["rendezvous"]
        assert rz["prepared"] == 0
        gangs = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "easy"]
        assert len(gangs) == 1 and gangs[0]["committed"]
        assert not gangs[0]["spans_dcn"]


def test_batch_dcn_commit_is_eager_then_kill_survives():
    """The batch driver binds every member in one drive: the
    rendezvous must read committed at the LAST BIND, not at the next
    janitor sweep — a replica killed in that window must not have its
    fully-committed gang aborted as 'part lost pre-commit'."""
    cfg = sharded_config(2, TPUKUBE_SNAPSHOT_AUDIT_RATE="1.0")
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        for g in ("fill-a", "fill-b"):
            grp = PodGroup(g, min_member=4)
            c.schedule_pending([
                c.make_pod(f"{g}-{i}", tpu=1, group=grp)
                for i in range(4)
            ])
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        c.schedule_pending([
            c.make_pod(f"d-{i}", tpu=1, group=gd) for i in range(8)
        ])
        rz = c.extender.statusz()["rendezvous"]
        assert rz["committed"] == 1 and rz["live"][0]["committed"]
        # kill a participant IMMEDIATELY (no sweep ran in between):
        # the committed gang survives, nothing is dissolved
        c.crash_replica(1)
        assert c.extender.sweep() == []
        restored = c.restart_replica(1)
        assert restored == 8  # fill-b + its committed dcn part
        parts = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "dcn"]
        assert len(parts) == 2 and all(g["committed"] for g in parts)
        settle(c)
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


def test_duplicate_prepare_is_idempotent():
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        # first member reserves through the rendezvous
        c.schedule(c.make_pod("dcn-0", tpu=1, group=gd))
        router = c.extender
        res_before = [
            rep.extender.gang.reservation("default", "dcn")
            for rep in router.replicas
        ]
        assert all(r is not None for r in res_before)
        # a duplicate filter for the same member (scheduler retry /
        # informer re-delivery) must not re-prepare: the SAME local
        # reservation objects stand and the prepared counter is flat
        from tpukube.sched import kube

        pod = c.pods["default/dcn-0"]
        router.handle("filter", {
            "Pod": pod,
            "NodeNames": list(router.state.node_names()),
        })
        res_after = [
            rep.extender.gang.reservation("default", "dcn")
            for rep in router.replicas
        ]
        assert all(a is b for a, b in zip(res_before, res_after))
        assert router.rendezvous_prepared == 1
        # gang-level duplicate prepare: reserve_exact_split for an
        # existing key returns the existing reservation verbatim
        rep = router.replicas[0]
        existing = rep.extender.gang.reservation("default", "dcn")
        from dataclasses import replace as dc_replace

        local_pod = dc_replace(
            kube.pod_from_k8s(pod),
            group=PodGroup(name="dcn",
                           min_member=existing.group.min_member,
                           allow_dcn=True),
        )
        again = rep.extender.gang.reserve_exact_split(
            local_pod, 1,
            {sid: sorted(cs)
             for sid, cs in existing.slice_coords.items()},
        )
        assert again is existing


def test_rendezvous_abort_on_timeout():
    """Members never bind: each part's local TTL sweep rolls its
    reservation back and the janitor aborts the rest — zero leaks."""
    clock = FakeClock()
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True,
                    clock=clock) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        # filter only (no bind): both parts reserved, nothing assigned
        pod = c.make_pod("dcn-0", tpu=1, group=gd)
        c._sync_nodes()
        fres = c.extender.handle("filter", {
            "Pod": pod,
            "NodeNames": list(c.extender.state.node_names()),
        })
        assert not fres.get("Error")
        assert c.extender.statusz()["rendezvous"]["prepared"] == 1
        clock.advance(cfg.reservation_ttl_seconds + 1)
        aborted = c.extender.sweep()
        assert ("default", "dcn") in aborted
        for rep in c.extender.replicas:
            assert rep.extender.gang.reservation("default", "dcn") \
                is None
        settle(c)
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


def test_rendezvous_abort_dissolves_bound_members():
    """A part lost before commit (TTL on one side while the other
    holds bound members) kills the WHOLE gang: bound members are
    evicted through the shared bus — all-or-nothing in death."""
    clock = FakeClock()
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True,
                    clock=clock) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        # bind three members (they land on the first part)
        for i in range(3):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        bound = [k for k, p in c.pods.items()
                 if k.startswith("default/dcn-")
                 and (p.get("spec") or {}).get("nodeName")]
        assert len(bound) == 3
        clock.advance(cfg.reservation_ttl_seconds + 1)
        aborted = c.extender.sweep()
        assert ("default", "dcn") in aborted
        settle(c)
        # every member's pod is gone (evicted), nothing reserved
        for k in bound:
            assert k not in c.pods
        assert all(
            rep.extender.gang.reservation("default", "dcn") is None
            for rep in c.extender.replicas
        )
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


# -- replica chaos (kill / partition mid-gang-commit) ------------------------

def chaos_cluster(clock=None):
    cfg = sharded_config(2, TPUKUBE_SNAPSHOT_AUDIT_RATE="1.0")
    return SimCluster(cfg, slices=two_slices(), in_process=True,
                      clock=clock)


def test_replica_kill_mid_commit_converges_zero_leaks():
    from tpukube.chaos import replica_crash_recover

    clock = FakeClock()
    with chaos_cluster(clock) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        for i in range(3):  # mid-commit: 3 of 8 bound
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        report = replica_crash_recover(c, 1)
        assert ["default", "dcn"] in report["rendezvous_aborted"]
        # the fill gang on s1 survives the crash (rebuilt from pods)
        assert report["restored_allocs"] == 4
        gangs = {g["group"]: g for g in c.extender.gang_snapshot()}
        assert gangs["fill-b"]["committed"]
        assert "dcn" not in gangs
        assert report["leaked_reservations"] == 0
        assert report["ledger_divergence"] == 0
        assert report["audit"]["divergences"] == 0
        # the plane keeps scheduling after recovery
        node, _ = c.schedule(c.make_pod("after", tpu=1))
        assert node


def test_replica_kill_after_commit_restores_part():
    """A participant killed AFTER the rendezvous committed restores
    its part by the LOCAL quorum — the committed gang survives."""
    with chaos_cluster() as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        for i in range(8):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        c.crash_replica(1)
        c.extender.sweep()
        restored = c.restart_replica(1)
        assert restored == 8  # fill-b (4) + its dcn part (4)
        parts = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "dcn"]
        assert len(parts) == 2 and all(g["committed"] for g in parts)
        rz = c.extender.statusz()["rendezvous"]
        assert rz["live"] and rz["live"][0]["committed"]
        settle(c)
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []
        assert c.extender.audit_stats()["divergences"] == 0


def test_replica_partition_mid_commit_heals_clean():
    """Partition (state survives, unreachable) mid-commit: the
    janitor aborts the rendezvous; the healed replica's leftover part
    — even a locally-complete one — is dissolved on heal, so no gang
    fragment resurrects."""
    clock = FakeClock()
    with chaos_cluster(clock) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        # bind part 0 (r0's 4 members) COMPLETELY, none of part 1:
        # r0's part is locally committed, the rendezvous is not
        for i in range(4):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        part0 = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "dcn" and g["committed"]]
        assert len(part0) == 1  # r0's part committed locally
        c.partition_replica(0)
        aborted = c.extender.sweep()
        assert ("default", "dcn") in aborted
        c.heal_replica(0)
        settle(c)
        # the locally-committed fragment did NOT survive the heal
        assert all(
            rep.extender.gang.reservation("default", "dcn") is None
            for rep in c.extender.replicas
        )
        assert all(k not in c.pods
                   for k in [f"default/dcn-{i}" for i in range(4)])
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []
        assert c.extender.audit_stats()["divergences"] == 0
        node, _ = c.schedule(c.make_pod("after", tpu=1))
        assert node


def test_killed_replica_ledger_not_served():
    """A KILLED replica's in-memory ledger died with the process: the
    federated views must show its pods ledger-absent until the warm
    restart (a partitioned replica's state, by contrast, is real and
    stays served)."""
    with chaos_cluster() as c:
        fill_slices(c)
        before = len(c.extender.state.allocations())
        assert before == 8
        c.crash_replica(1)
        assert len(c.extender.state.allocations()) == 4
        assert all(g["group"] == "fill-a"
                   for g in c.extender.gang_snapshot())
        c.restart_replica(1)
        assert len(c.extender.state.allocations()) == 8
        # partition: state survives and IS served
        c.partition_replica(0)
        assert len(c.extender.state.allocations()) == 8
        c.heal_replica(0)


def test_aborted_rendezvous_name_reuse_not_sentenced():
    """A gang re-created with the SAME name after an abort — while the
    partitioned replica is still down — must not be dissolved when
    that replica later heals: the abort sentence is scoped to the
    replicas that were unreachable, not to the gang name."""
    clock = FakeClock()
    with chaos_cluster(clock) as c:
        fill_slices(c)
        gd = PodGroup("dcn", min_member=8, allow_dcn=True)
        for i in range(2):
            c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=gd))
        c.partition_replica(1)
        assert ("default", "dcn") in c.extender.sweep()
        settle(c)
        # re-create the same-named gang while r1 is still down: it
        # must fit whole on r0 (free the fill gang there first)
        for i in range(4):
            c.complete_pod(f"fill-a-{i}")
        gd2 = PodGroup("dcn", min_member=4, allow_dcn=True)
        for i in range(4):
            c.schedule(c.make_pod(f"re-{i}", tpu=1, group=gd2))
        # pre-heal: the partitioned replica's stale fragment is still
        # SERVED (its state is real until heal) — the new gang is the
        # one committed entry
        committed = [g for g in c.extender.gang_snapshot()
                     if g["group"] == "dcn" and g["committed"]]
        assert len(committed) == 1
        # heal: r1's stale fragment dies, r0's LIVE gang survives
        c.heal_replica(1)
        settle(c)
        gangs = [g for g in c.extender.gang_snapshot()
                 if g["group"] == "dcn"]
        assert len(gangs) == 1 and gangs[0]["committed"]
        assert all(f"default/re-{i}" in c.pods for i in range(4))
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


def test_malformed_request_reports_schema_error():
    """A pod asking for BOTH resources must get the schema error from
    a replica, byte-for-byte like the unsharded planner — never a
    silent feasible-everywhere answer."""
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        c.schedule(c.make_pod("warm", tpu=1))
        pod = c.make_pod("bad", tpu=1, vtpu=1)
        fres = c.extender.handle("filter", {
            "Pod": pod,
            "NodeNames": list(c.extender.state.node_names()),
        })
        assert "requests both" in fres["Error"]
        assert fres["NodeNames"] == []


def test_partitioned_replica_binds_fail_retryably():
    with chaos_cluster() as c:
        # route a pod to each replica first so the maps are warm
        c.schedule(c.make_pod("warm-0", tpu=1))
        router = c.extender
        router.partition_replica(1)
        # a bind landing on the dead replica's node fails with a
        # retryable error, not an exception
        name = next(n for n, i in router._node_replica.items()
                    if i == 1)
        out = router.handle("bind", {
            "PodName": "ghost", "PodNamespace": "default",
            "PodUID": "", "Node": name,
        })
        assert "unavailable" in out["Error"]
        # non-gang pods spill over to the alive replica
        node, _ = c.schedule(c.make_pod("spill", tpu=1))
        assert router._node_replica[node] == 0


# -- routing -----------------------------------------------------------------

def test_nongang_spillover_when_primary_full():
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        placed = []
        for i in range(16):  # exactly the fleet's capacity
            node, _ = c.schedule(c.make_pod(f"p-{i}", tpu=1))
            placed.append(node)
        # both replicas' slices filled — the hash alone cannot have
        # sent every pod to its own-half only
        assert {n.split("-")[0] for n in placed} == {"s0", "s1"}
        with pytest.raises(RuntimeError):
            c.schedule(c.make_pod("p-overflow", tpu=1), retries=2)
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


def test_gang_reroutes_after_transient_full_fleet():
    """A gang that fit NOWHERE (error answer) must not stay pinned to
    the replica that owned the error: once capacity frees anywhere,
    the retry re-probes the fleet and reserves there."""
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        placed = {}
        for i in range(16):  # fill the whole fleet
            node, _ = c.schedule(c.make_pod(f"p-{i}", tpu=1))
            placed[f"p-{i}"] = node
        g = PodGroup("late", min_member=4)
        with pytest.raises(RuntimeError):
            c.schedule(c.make_pod("late-0", tpu=1, group=g), retries=2)
        # free one replica's slice entirely
        for name, node in placed.items():
            if node.startswith("s1"):
                c.delete_pod(name)
        for j in range(4):
            c.schedule(c.make_pod(f"late-{j}", tpu=1, group=g))
        gangs = {x["group"]: x for x in c.extender.gang_snapshot()}
        assert gangs["late"]["committed"]
        assert leaked_reservations(c) == []


def test_release_routes_and_frees():
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"p-{i}", tpu=1))
        before = len(c.extender.state.allocations())
        c.delete_pod("p-0")
        assert len(c.extender.state.allocations()) == before - 1
        assert c.extender.state.allocation("default/p-0") is None


def test_statusz_and_metrics_render():
    cfg = sharded_config()
    with SimCluster(cfg, slices=two_slices(), in_process=True) as c:
        c.schedule(c.make_pod("p-0", tpu=1))
        doc = c.extender.statusz()
        assert {r["replica"] for r in doc["replicas"]} == {"r0", "r1"}
        assert doc["slice_assignment"] == {"s0": "r0", "s1": "r1"}
        from tpukube.metrics import render_router_metrics

        text = render_router_metrics(c.extender)
        assert "tpukube_router_replicas 2" in text
        assert 'tpukube_replica_nodes{replica="r0"}' in text


# -- filter answers from the plan (ISSUE 13 satellite) ------------------------

def test_filter_from_plan_parity_and_minimal_answer():
    """With filter_from_plan, webhook placements are identical but the
    feasibility answer is the planned node alone — the O(nodes)
    materialization is gone."""
    base_env = {"TPUKUBE_BATCH_ENABLED": "1"}
    placements: dict[str, dict[str, str]] = {}
    for mode, extra in (
        ("full", {}),
        ("plan", {"TPUKUBE_FILTER_FROM_PLAN": "1"}),
    ):
        cfg = load_config(env={**base_env, **extra})
        mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1),
                        torus=(False, False, False))
        with SimCluster(cfg, mesh=mesh, in_process=True) as c:
            got = {}
            grp = PodGroup("pg", min_member=2)
            for i in range(4):
                node, _ = c.schedule(c.make_pod(f"s-{i}", tpu=1))
                got[f"s-{i}"] = node
            for i in range(2):
                node, _ = c.schedule(
                    c.make_pod(f"g-{i}", tpu=1, group=grp))
                got[f"g-{i}"] = node
            placements[mode] = got
            if mode == "plan":
                # the wire answer is minimal: one feasible node
                pod = c.make_pod("probe", tpu=1)
                fres = c.extender.handle("filter", {
                    "Pod": pod,
                    "NodeNames": list(c.extender.state.node_names()),
                })
                assert len(fres["NodeNames"]) == 1
                assert fres["FailedNodes"] == {}
    assert placements["full"] == placements["plan"]


def test_filter_from_plan_requires_batching():
    with pytest.raises(ValueError, match="filter_from_plan"):
        load_config(env={"TPUKUBE_FILTER_FROM_PLAN": "1"})


# -- incremental occupied sets (ISSUE 13 satellite) ---------------------------

def test_incremental_occupied_matches_walk_through_lifecycle():
    cfg = load_config(env={})
    mesh = MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1),
                    torus=(False, False, False))
    with SimCluster(cfg, mesh=mesh) as c:
        st = c.extender.state

        def check():
            sid = st.slice_ids()[0]
            assert st.occupied_coords(sid) == \
                st.walk_occupied_coords(sid)

        c.schedule(c.make_pod("a", tpu=1))
        check()
        c.schedule(c.make_pod("b", tpu=2))
        check()
        # health flip (health-only re-annotation path)
        c.inject_fault("host-0-0-0", 1)
        c.schedule(c.make_pod("c", tpu=1))
        check()
        c.inject_fault("host-0-0-0", 1, healthy=True)
        c.schedule(c.make_pod("d", tpu=1))
        check()
        # release
        c.delete_pod("a")
        check()
        # structural re-annotation (link fault changes bad_links)
        c.inject_link_fault((0, 0, 0), (0, 0, 1))
        c.schedule(c.make_pod("e", tpu=1))
        check()


def test_scenario14_smoke(monkeypatch):
    """tpukube-sim 14 at tier-1 scale: 2 tiny slices behind 2 planner
    replicas, full invariants (the scenario raises on leaks,
    divergence, shortfall, or a dead replica) — run under the dynamic
    lock-order monitor, asserting the fleet-merged lockgraph (router +
    worker edges) stays cycle-free (ISSUE 18 acceptance)."""
    monkeypatch.setenv("TPUKUBE_SHARD_SLICES", "2")
    monkeypatch.setenv("TPUKUBE_SIM_MESH_DIMS", "4,4,4")
    monkeypatch.setenv("TPUKUBE_PLANNER_REPLICAS", "2")
    monkeypatch.setenv("TPUKUBE_KILONODE100K_PODS", "400")
    monkeypatch.setenv("TPUKUBE_LOCK_MONITOR", "1")
    from tpukube.sim import scenarios

    r = scenarios.run(14)
    assert r["scenario"] == 14
    assert r["pods_total"] >= 400
    assert r["ledger_divergence"] == 0
    assert r["gang_committed"]
    assert len(r["shard"]["replicas"]) == 2
    assert all(x["alive"] for x in r["shard"]["replicas"])
    assert set(r["shard"]["slice_assignment"].values()) == {"r0", "r1"}
    lg = r["shard"]["lock_graph"]
    assert lg["cycles"] == [], lg["cycles"]
    assert lg["acquisitions"] > 0
    assert lg["replicas_reporting"] == ["r0", "r1"]


def test_config_validation_replicas():
    with pytest.raises(ValueError, match="planner_replicas"):
        load_config(env={"TPUKUBE_PLANNER_REPLICAS": "0"})
    with pytest.raises(ValueError, match="shard-aware"):
        load_config(env={
            "TPUKUBE_PLANNER_REPLICAS": "2",
            "TPUKUBE_TENANCY_ENABLED": "1",
            "TPUKUBE_TENANCY_QUOTAS": "a=chips:4",
        })

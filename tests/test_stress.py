"""Concurrency stress: the reservation table + ledger must stay linearizable
under racing webhook cycles (SURVEY.md §9.3 "gang atomicity … reservations
must be linearizable under concurrent filter calls").

Mixed load on one live extender: two competing gangs, a herd of solo pods,
and concurrent deletes. Whatever interleaving happens, the ledger
invariants must hold: no chip double-allocated, gangs all-or-nothing and
contiguous, utilization consistent with the ledger.
"""

import threading

from tpukube.core.config import load_config
from tpukube.core.types import PodGroup, TopologyCoord
from tpukube.sim import SimCluster


def _box_contiguous(coords: list[TopologyCoord]) -> bool:
    xs = sorted({c[0] for c in coords})
    ys = sorted({c[1] for c in coords})
    zs = sorted({c[2] for c in coords})
    if len(xs) * len(ys) * len(zs) != len(set(coords)):
        return False
    return all(
        axis == list(range(axis[0], axis[0] + len(axis)))
        for axis in (xs, ys, zs)
    )


def test_concurrent_mixed_load_keeps_ledger_consistent():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        errs: list[str] = []
        lock = threading.Lock()
        g1 = PodGroup("alpha", min_member=8)
        g2 = PodGroup("beta", min_member=8)

        def sched(name, **kw):
            try:
                c.schedule(c.make_pod(name, tpu=1, **kw))
            except RuntimeError as e:
                # legitimate under contention (full cluster / lost race
                # budget); anything else is a real bug
                with lock:
                    errs.append(f"{name}: {e}")

        threads = (
            [threading.Thread(target=sched, args=(f"a-{i}",),
                              kwargs={"group": g1, "priority": 10})
             for i in range(8)]
            + [threading.Thread(target=sched, args=(f"b-{i}",),
                                kwargs={"group": g2, "priority": 10})
               for i in range(8)]
            + [threading.Thread(target=sched, args=(f"solo-{i}",))
               for i in range(12)]
        )
        for t in threads:
            t.start()
        # concurrent deletes of solo pods while gangs assemble
        deleters = []
        for i in range(4):
            d = threading.Thread(target=c.delete_pod, args=(f"solo-{i}",))
            deleters.append(d)
            d.start()
        for t in threads + deleters:
            t.join()

        state = c.extender.state
        allocs = state.allocations()

        # 1. no chip is allocated to two pods
        seen: dict[tuple, str] = {}
        for a in allocs:
            for co in a.coords:
                key = tuple(co)
                assert key not in seen, (
                    f"chip {key} allocated to both {seen[key]} and {a.pod_key}"
                )
                seen[key] = a.pod_key

        # 2. utilization agrees with the ledger
        assert state.utilization() == len(seen) / 32

        # 3. gangs are all-or-nothing: each is either fully bound on a
        # contiguous box or completely absent from the ledger
        for gname in ("alpha", "beta"):
            members = [a for a in allocs if a.pod_key.startswith(f"default/{gname[0]}-")]
            res = c.extender.gang.reservation("default", gname)
            if res is not None and res.committed:
                assert len(members) == 8, f"{gname}: {len(members)} bound"
                coords = [co for a in members for co in a.coords]
                assert _box_contiguous(coords), f"{gname}: {sorted(coords)}"
            else:
                assert members == [], (
                    f"{gname} uncommitted but {len(members)} members hold chips"
                )

        # 4. both 8-chip gangs fit in 32 chips minus 12 solos — with this
        # load both MUST have committed; schedule failures may only be
        # solo-pod contention
        for gname in ("alpha", "beta"):
            res = c.extender.gang.reservation("default", gname)
            assert res is not None and res.committed, (gname, errs)
        gang_errs = [e for e in errs if e[0] in "ab"]
        assert not gang_errs, gang_errs


def test_concurrent_preemption_with_graceful_victims():
    """The round-5 termination gate under racing schedulers: a full
    cluster of low-priority solos, then a high-priority gang whose
    members are driven by CONCURRENT scheduler threads while victims
    terminate gracefully in the background. Whatever the interleaving:
    no gang member may ever hold a chip while its victim's pod object
    still exists, no chip double-allocates, and the gang lands whole."""
    import time

    from tpukube import apiserver as apisrv

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        for i in range(16):
            pod = c.make_pod(f"s-{i}", tpu=1, priority=5)
            c.schedule(pod)
            api.upsert_pod(pod)
            api.graceful.add(f"default/s-{i}")
        ext = c.extender
        ext.evict_precheck = (
            lambda pk: api.evict_pod(*pk.split("/", 1), dry_run=True)
        )
        execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
        # schedule()'s internal drain must run THIS executor (graceful
        # victims), not the pod store's instant-delete one — otherwise
        # the gate never sees a termination window at all
        c._evictions = execu

        overlap_errs: list[str] = []
        errs: list[str] = []
        lock = threading.Lock()
        stop = threading.Event()

        def finisher():
            """Plays the kubelets: terminating victims finish at random
            times; the executor confirms and dispatches victim_gone."""
            while not stop.is_set():
                execu.drain()
                for pod in api.list_pods():
                    meta = pod["metadata"]
                    if meta.get("deletionTimestamp"):
                        # a gang member must not hold chips while ANY
                        # victim object still exists
                        gang_bound = [
                            a.pod_key for a in ext.state.allocations()
                            if a.pod_key.startswith("default/vip-")
                        ]
                        if gang_bound:
                            with lock:
                                overlap_errs.append(
                                    f"{gang_bound} bound while "
                                    f"{meta['name']} still terminating"
                                )
                        api.finish_termination(meta["namespace"],
                                               meta["name"])
                execu.drain()
                time.sleep(0.002)

        fin = threading.Thread(target=finisher)
        fin.start()

        gang = PodGroup("vip", min_member=8)

        def sched(name):
            try:
                c.schedule(c.make_pod(name, tpu=1, priority=100,
                                      group=gang), retries=200)
            except RuntimeError as e:
                with lock:
                    errs.append(f"{name}: {e}")

        threads = [threading.Thread(target=sched, args=(f"vip-{i}",))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        fin.join()

        assert not overlap_errs, overlap_errs[:3]
        assert not errs, errs[:3]
        res = ext.gang.reservation("default", "vip")
        assert res is not None and res.committed
        # no chip double-allocated
        seen: dict[tuple, str] = {}
        for a in ext.state.allocations():
            for co in a.coords:
                assert tuple(co) not in seen, (co, a.pod_key, seen)
                seen[tuple(co)] = a.pod_key
        assert ext.gang.terminating_count() == 0


def test_restart_under_load_rebuilds_identical_state():
    """Kill-and-rebuild mid-scenario: the restarted extender must agree
    with the pods' annotations exactly (SURVEY.md §6 checkpoint/resume)."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        g = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=5, group=g))
        for i in range(3):
            c.schedule(c.make_pod(f"s-{i}", tpu=1))
        c.delete_pod("s-1")
        before = {
            a.pod_key: (a.node_name, tuple(map(tuple, a.coords)))
            for a in c.extender.state.allocations()
        }

        from tpukube.sched.extender import Extender
        fresh = Extender(cfg)
        for obj in c.node_objects():
            fresh.state.upsert_node(obj["metadata"]["name"],
                                    obj["metadata"]["annotations"])
        n = fresh.rebuild_from_pods(
            [p["metadata"]["annotations"] for p in c.pods.values()]
        )
        assert n == len(before) == 6
        after = {
            a.pod_key: (a.node_name, tuple(map(tuple, a.coords)))
            for a in fresh.state.allocations()
        }
        assert after == before
        res = fresh.gang.reservation("default", "g")
        assert res is not None and res.committed
        # restored gang keeps all-or-nothing protection: its members are
        # not individually preemptable as free-standing pods
        assert {k for k in res.assigned} == {
            f"default/g-{i}" for i in range(4)
        }


def test_v5p_2048_scale_budget():
    """Scheduling must stay interactive at v5p-2048 scale (2048 chips,
    512 hosts): a 64-pod gang and a batch of singles each within a budget
    ~10x the measured wall (CI headroom, catches complexity cliffs)."""
    import time

    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import PodGroup

    mesh = MeshSpec(dims=(16, 16, 8), host_block=(2, 2, 1))
    with SimCluster(load_config(env={}), mesh=mesh) as c:
        t0 = time.perf_counter()
        g = PodGroup("big", min_member=64)
        for i in range(64):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=g))
        gang_wall = time.perf_counter() - t0
        assert c.extender.gang.reservation("default", "big").committed
        t1 = time.perf_counter()
        for i in range(32):
            c.schedule(c.make_pod(f"s-{i}", tpu=1))
        singles_wall = time.perf_counter() - t1
        assert gang_wall < 10.0, f"64-pod gang took {gang_wall:.1f}s"
        assert singles_wall < 10.0, f"32 singles took {singles_wall:.1f}s"

"""ISSUE 20: compact binary wire codec for the sharded driver surface.

The acceptance gates covered here:
  * every /worker op schema round-trips ``decode(encode(x)) == x`` on
    seeded fleet-shaped bodies (node payloads with badLinks, pod
    lists, alloc deltas) — JSON stays the parity oracle;
  * placements are bit-identical ``wire_codec: binary`` vs ``json``
    over real worker daemons, and the default (json) plane's wire
    accounting/exposition shape is untouched;
  * a truncated/corrupt TKW1 frame answers HTTP 400 and leaves the
    worker serving — never a crash, never a spuriously dead replica;
  * per-request Content-Type/Accept negotiation: a binary router over
    a JSON-only worker degrades cleanly to JSON (rolling upgrades),
    and a respawned worker re-handshakes from JSON;
plus the satellites: compact JSON separators on the codec-off path,
failed requests billed into the wire counters, codec-tagged
``wire_by_op`` cells, and chaos (worker SIGKILL/restart) green over
the binary transport.

Worker daemons are real subprocesses; tests that need them skip
gracefully where spawning is unavailable.
"""

from __future__ import annotations

import http.client
import json
import math
import random
import threading
from collections import deque

import pytest

from tpukube.chaos import ledger_divergence
from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.sched import wirecodec
from tpukube.sched.wirecodec import (
    WireCodecError,
    decode_frame,
    dumps_json,
    encode_frame,
)
from tpukube.sim.harness import SimCluster


def can_spawn_workers() -> bool:
    from tpukube.sched.shard import ShardError, SubprocessTransport

    try:
        probe = SubprocessTransport(0, load_config(env={}),
                                    fake_clock=False)
        probe.close()
        return True
    except (ShardError, OSError):
        return False


needs_workers = pytest.mark.skipif(
    not can_spawn_workers(),
    reason="cannot spawn shard-worker subprocesses here",
)


def proc_config(n: int, **extra: str):
    return load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_BATCH_ENABLED": "1",
        **extra,
    })


def two_slices(dims=(2, 2, 2)) -> dict[str, MeshSpec]:
    return {
        sid: MeshSpec(dims=dims, host_block=(2, 2, 1),
                      torus=(False, False, False))
        for sid in ("s0", "s1")
    }


# -- fleet-shaped op bodies ---------------------------------------------------

def _pod_obj(rng: random.Random, i: int) -> dict:
    from tpukube.core import codec as core_codec

    grp = (core_codec.pod_group_annotations(
        PodGroup(f"g{i % 5}", min_member=rng.randint(2, 8),
                 allow_dcn=bool(i % 2)))
        if i % 3 == 0 else {})
    return {
        "metadata": {"name": f"pod-{i}", "namespace": "default",
                     "uid": f"uid-{i:06d}",
                     "annotations": grp,
                     "labels": {"team": f"t{i % 4}"}},
        "spec": {"priority": rng.randint(0, 100), "containers": [{
            "name": "main",
            "resources": {"requests": {
                "qiniu.com/tpu": str(rng.choice([1, 2, 4]))}},
        }]},
    }


def _node_item(rng: random.Random, i: int) -> dict:
    """An upsert_nodes item shaped like the fleet ingest payloads:
    annotation JSON with device ids, coords, and occasional badLinks
    — the KubeGPU-lineage body the codec exists to compress."""
    name = f"tpu-v4-{i // 64:02d}-{i % 64:03d}"
    return {
        "name": name,
        "slice_id": f"s{i // 64:02d}",
        "topology": "16x16x40",
        "chips": 4,
        "device_ids": [f"{name}-chip-{d}" for d in range(4)],
        "coords": [[i % 16, (i // 16) % 16, i % 40 + d]
                   for d in range(4)],
        "badLinks": ([] if i % 11 else
                     [{"from": f"{name}-chip-0",
                       "to": f"{name}-chip-1",
                       "kind": "ici"}]),
        "hbm_bytes": 34359738368,
        "free": rng.choice([0, 2, 4]),
        "epoch": rng.randint(0, 40),
        "healthy": i % 13 != 0,
    }


def _alloc_obj(rng: random.Random, i: int) -> dict:
    node = f"tpu-v4-00-{i % 64:03d}"
    n = rng.choice([1, 2, 4])
    return {
        "pod_key": f"default/job-{i}",
        "node_name": node,
        "device_ids": [f"{node}-chip-{d}" for d in range(n)],
        "coords": [[i % 16, i % 16, (i + d) % 40] for d in range(n)],
        "slice_id": f"s{i % 4:02d}",
    }


def _op_bodies(seed: int) -> dict[str, object]:
    """One representative body per /worker op (requests AND the
    response shapes the worker sends back)."""
    rng = random.Random(seed)
    return {
        "upsert": {"items": [_node_item(rng, i) for i in range(96)]},
        "admit": {"pods": [_pod_obj(rng, i) for i in range(48)]},
        "planned": {"keys": [f"default/pod-{i}" for i in range(128)]},
        "planned_resp": {"nodes": {
            f"default/pod-{i}": (f"tpu-v4-00-{i % 64:03d}"
                                 if i % 5 else None)
            for i in range(128)}},
        "bind": {"bodies": [{
            "Pod": _pod_obj(rng, i),
            "Node": f"tpu-v4-00-{i % 64:03d}",
        } for i in range(32)]},
        "release": {"keys": [f"default/pod-{i}" for i in range(64)]},
        "handle": {"kind": "filter", "body": {
            "Pod": _pod_obj(rng, 0),
            "NodeNames": [f"tpu-v4-00-{i:03d}" for i in range(64)],
        }},
        "gang_prepare": {"op": "prepare", "pod": _pod_obj(rng, 3),
                         "cpp": 4,
                         "volumes": {f"s{i:02d}": rng.randint(0, 64)
                                     for i in range(4)}},
        "gauges_resp": {"slices": {f"s{i:02d}": {
            "free": rng.randint(0, 4096),
            "largest_free_box": [rng.randint(1, 16) for _ in range(3)],
            "nodes": 256, "unhealthy": rng.randint(0, 3),
        } for i in range(4)}},
        "allocs_since_resp": {
            "cursor": [3, rng.randint(100, 10_000)],
            "bytes": rng.randint(0, 1 << 20),
            "adds": [_alloc_obj(rng, i) for i in range(80)],
            "removes": [f"default/job-{i}" for i in range(40)],
        },
        "allocs_resp": {"allocs": [_alloc_obj(rng, i)
                                   for i in range(120)]},
        "recover": {"nodes": [_node_item(rng, i) for i in range(32)],
                    "pods": [_pod_obj(rng, i) for i in range(32)]},
        "rebuild": {"pods": [{
            "pod_key": f"default/job-{i}",
            "node": f"tpu-v4-00-{i % 64:03d}",
            "devices": f"{i % 4}",
        } for i in range(48)]},
        "emit": {"reason": "Scheduled", "obj": "default/pod-1",
                 "message": "bound 4 chips", "type": "Normal"},
        "advance": {"seconds": 2.5},
        "summary_resp": {"nodes": 1024, "allocs": 512,
                         "binds_total": 9999,
                         "utilization": 0.8125,
                         "queue_depth": 0,
                         "slices": ["s00", "s01"],
                         "latencies": {"filter_ms": [0.5, 1.25]},
                         "events": {"emitted": 42}},
    }


# -- round-trip property ------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1337, 90210])
def test_every_op_schema_roundtrips(seed):
    for op, body in _op_bodies(seed).items():
        for compress_min in (0, 1024, 1 << 30):
            frame, raw_len = encode_frame(body, compress_min)
            assert decode_frame(frame) == body, (op, compress_min)
            assert raw_len > 0


def test_hot_bodies_beat_compact_json():
    """The per-op key tables + interning + compression must collapse
    the hot dict-list bodies well below compact JSON — the bytes/wave
    acceptance depends on it."""
    bodies = _op_bodies(7)
    for op in ("upsert", "admit", "allocs_since_resp", "allocs_resp",
               "planned_resp", "bind", "recover"):
        body = bodies[op]
        frame, _ = encode_frame(body, 1024)
        jlen = len(dumps_json(body))
        assert len(frame) < jlen / 2, \
            f"{op}: frame {len(frame)} vs json {jlen}"


def test_scalar_edge_values_roundtrip():
    cases = [
        None, True, False, 0, 1, -1, 2**62, -(2**62), 10**18,
        0.0, 1.5, -2.75, 1e308, -1e308, 5e-324,
        "", "x", "ü" * 21, "x" * 64, "y" * 65, "z" * 100_000,
        [], {}, [[]], [{}], {"": ""}, {"k": []},
        [1, "1", 1.0, True, None],
        {"nested": {"deep": [{"a": [1, [2, [3, {"b": None}]]]}]}},
    ]
    for v in cases:
        frame, _ = encode_frame(v, 1 << 30)
        out = decode_frame(frame)
        assert out == v
        # 1 vs True / 1.0 vs 1: json's type fidelity is the oracle
        assert type(out) is type(v)
    # float specials: -0.0 keeps its sign, inf survives, nan is nan
    assert math.copysign(1, decode_frame(
        encode_frame(-0.0, 1 << 30)[0])) == -1
    assert decode_frame(
        encode_frame(math.inf, 1 << 30)[0]) == math.inf
    assert math.isnan(decode_frame(
        encode_frame(math.nan, 1 << 30)[0]))


def test_heterogeneous_dict_lists_roundtrip():
    """Lists of dicts with MISMATCHED keys must skip the table path
    and still round-trip exactly."""
    v = {"rows": [{"a": 1}, {"a": 1, "b": 2}, {"b": 2, "a": 1},
                  {"c": 3}, {}, "not-a-dict", [1], None]}
    frame, _ = encode_frame(v, 1 << 30)
    assert decode_frame(frame) == v


def test_intern_rule_symmetric_over_64_bytes():
    # a >64-byte string repeats: encoded twice (never interned), and
    # the decoder must not grow its table for it
    big = "n" * 65
    v = [big, big, "small", "small"]
    frame, _ = encode_frame(v, 1 << 30)
    assert decode_frame(frame) == v
    # repeated small strings DO pay only once
    many_small = ["node-abc"] * 100
    f_small, _ = encode_frame(many_small, 1 << 30)
    assert len(f_small) < 100 * 8


def test_compression_threshold_and_keep_raw():
    body = {"items": [_node_item(random.Random(1), i)
                      for i in range(64)]}
    raw_frame, raw_len = encode_frame(body, 1 << 30)
    comp_frame, comp_raw = encode_frame(body, 0)
    assert raw_len == comp_raw
    assert decode_frame(comp_frame) == decode_frame(raw_frame) == body
    assert len(comp_frame) < len(raw_frame)
    # incompressible payloads stay raw even above the threshold
    noise = "".join(chr(0x100 + random.Random(2).randrange(0x4000))
                    for _ in range(4096))
    f_noise, _ = encode_frame(noise, 0)
    assert f_noise[4] in (0, 1, 2)  # valid flag either way
    assert decode_frame(f_noise) == noise


# -- garbage-frame fuzz -------------------------------------------------------

def test_garbage_frames_fuzz():
    """Truncations, bit flips, bad magic, bad flags, trailing bytes:
    decode must raise WireCodecError — never IndexError/KeyError/
    MemoryError/hang — or succeed (a lucky mutation)."""
    rng = random.Random(4242)
    body = _op_bodies(1)["upsert"]
    frames = [encode_frame(body, 1 << 30)[0],
              encode_frame(body, 0)[0],
              encode_frame({"k": list(range(100))}, 1 << 30)[0]]
    cases = [b"", b"T", b"TKW1", b"TKW2" + frames[0][4:],
             frames[0] + b"\x00", bytes([255]) * 64]
    for f in frames:
        for cut in (5, 6, len(f) // 2, len(f) - 1):
            cases.append(f[:cut])
        for _ in range(200):
            mutated = bytearray(f)
            for _ in range(rng.randint(1, 4)):
                mutated[rng.randrange(len(mutated))] = \
                    rng.randrange(256)
            cases.append(bytes(mutated))
    decoded = failed = 0
    for case in cases:
        try:
            decode_frame(case)
            decoded += 1
        except WireCodecError:
            failed += 1
    # every outcome accounted for: nothing escaped as another type
    assert decoded + failed == len(cases)
    assert failed > len(cases) // 2


def test_adversarial_counts_bounded():
    """A frame claiming a huge list/table row count must fail fast on
    the length-vs-remaining-bytes check, not allocate gigabytes."""
    import io
    import struct as _struct

    out = io.BytesIO()
    out.write(b"TKW1\x00\x08")  # list tag
    # varint 2**40 elements, no payload
    n = 1 << 40
    while True:
        b = n & 0x7F
        n >>= 7
        out.write(bytes((b | 0x80,)) if n else bytes((b,)))
        if not n:
            break
    with pytest.raises(WireCodecError):
        decode_frame(out.getvalue())


# -- the JSON path (codec off) ------------------------------------------------

def test_dumps_json_compact_separators():
    body = {"a": [1, 2], "b": {"c": True}}
    assert dumps_json(body) == b'{"a":[1,2],"b":{"c":true}}'
    assert json.loads(dumps_json(_op_bodies(0)["upsert"])) == \
        _op_bodies(0)["upsert"]


def test_config_validation():
    assert load_config(env={}).wire_codec == "json"
    assert load_config(env={}).wire_compress_min_bytes == 1024
    cfg = load_config(env={"TPUKUBE_WIRE_CODEC": "binary"})
    assert cfg.wire_codec == "binary"
    # binary + inprocess is NOT an error: worker YAMLs carry it (the
    # router pins every worker's own transport to inprocess)
    assert cfg.shard_transport == "inprocess"
    with pytest.raises(ValueError, match="wire_codec"):
        load_config(env={"TPUKUBE_WIRE_CODEC": "msgpack"})
    with pytest.raises(ValueError, match="wire_compress_min_bytes"):
        load_config(env={"TPUKUBE_WIRE_COMPRESS_MIN_BYTES": "-1"})


# -- negotiation against a JSON-only peer (rolling upgrade) -------------------

class _JsonOnlyHandler:
    """A pre-codec worker: answers compact JSON to everything and
    ignores Accept — what a mixed-version fleet's old daemons do."""


def _stub_transport(port: int, codec: str = "binary"):
    """A SubprocessTransport pointed at a stub server: __new__ skips
    the daemon spawn, fields mirror __init__."""
    from tpukube.sched.shard import SubprocessTransport

    t = object.__new__(SubprocessTransport)
    t.index = 0
    t.on_down = None
    t.down = False
    t.health_checks = 0
    t.health_failures = 0
    t.rtt_window = deque(maxlen=SubprocessTransport.RTT_WINDOW)
    t.rtt_sum = 0.0
    t.rtt_count = 0
    t.wire_tx = 0
    t.wire_rx = 0
    t.wire_by_op = {}
    t.wire_codec = codec
    t.wire_compress_min_bytes = 64
    t.wire_raw_tx = 0
    t.wire_raw_rx = 0
    t._peer_binary = None
    t.on_wire = None
    t._lock = threading.Lock()
    t._conn = None
    t._port = port
    return t


@pytest.fixture()
def json_only_server():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            # a JSON-only worker would 400 on a binary body; the
            # negotiating router never sends one unprompted
            if body.startswith(b"TKW1"):
                self.send_response(400)
                self.end_headers()
                return
            doc = json.loads(body) if body else {}
            out = json.dumps({"echo": doc}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        yield srv.server_address[1]
    finally:
        srv.shutdown()
        srv.server_close()


def test_binary_router_degrades_to_json_only_worker(json_only_server):
    """A binary-codec router against a JSON-only peer: every request
    stays JSON (the Accept probe is simply ignored), nothing errors,
    and the wire accounting never tags the op binary."""
    t = _stub_transport(json_only_server, codec="binary")
    for _ in range(3):
        out = t._request("POST", "/worker/planned",
                         {"keys": ["default/a", "default/b"]})
        assert out == {"echo": {"keys": ["default/a", "default/b"]}}
    assert t._peer_binary is None  # peer never answered TKW1
    snap = t.wire_snapshot()
    assert snap["codec"] == "binary"  # configured...
    assert "codec" not in snap["by_op"]["planned"]  # ...never used
    assert snap["by_op"]["planned"]["calls"] == 3


def test_failed_requests_billed(json_only_server):
    """The satellite: a request that raises after conn.request still
    bills its tx bytes and bumps a failures counter — retry storms
    must show in the wire bill."""
    t = _stub_transport(json_only_server, codec="json")
    t._port = 1  # nothing listens there
    from tpukube.sched.shard import ReplicaUnavailable

    body = {"keys": ["default/x" * 10]}
    with pytest.raises(ReplicaUnavailable):
        t._request("POST", "/worker/planned", body, mark_down=False,
                   timeout=2.0)
    snap = t.wire_snapshot()
    cell = snap["by_op"]["planned"]
    assert cell["failures"] == 1
    assert cell["calls"] == 1
    assert cell["tx"] == len(dumps_json(body))
    assert cell["rx"] == 0
    assert not t.down  # mark_down=False: billed but not condemned


# -- real worker daemons ------------------------------------------------------

@needs_workers
def test_corrupt_frame_answers_400_worker_keeps_serving():
    """A truncated/corrupt TKW1 body reaches a REAL worker daemon: the
    worker answers 400 and keeps serving; the transport raises
    ShardError (a request defect), never marks the replica dead."""
    from tpukube.sched.shard import ShardError, SubprocessTransport

    t = SubprocessTransport(0, load_config(env={}), fake_clock=True)
    try:
        frame, _ = encode_frame({"keys": ["default/x"]}, 1 << 30)
        for evil in (frame[:-3], b"TKW1\x07garbage", b"TKW9" + frame[4:]):
            conn = http.client.HTTPConnection("127.0.0.1", t._port,
                                              timeout=10)
            conn.request(
                "POST", "/worker/planned", body=evil,
                headers={"Content-Type": wirecodec.WIRE_CONTENT_TYPE})
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            assert resp.status == 400, (evil[:12], resp.status)
            assert b"bad wire frame" in body
        # the worker still serves; the replica is not dead
        assert t.healthz()
        assert not t.down
        out = t._request("POST", "/worker/planned",
                         {"keys": ["default/x"]})
        assert out == {"nodes": {"default/x": None}}
        # a defective request is a ShardError (HTTP 4xx), never a
        # ReplicaUnavailable: the transport stays up
        with pytest.raises(ShardError):
            t._request("POST", "/worker/no-such-op", {})
        assert not t.down
    finally:
        t.kill()


@needs_workers
def test_negotiation_upgrades_and_accounts():
    """First contact is a JSON probe; a TKW1 answer upgrades request
    bodies to binary; the codec-tagged cells carry raw vs wire
    bytes."""
    import dataclasses

    from tpukube.sched.shard import SubprocessTransport

    cfg = dataclasses.replace(load_config(env={}),
                              wire_codec="binary",
                              wire_compress_min_bytes=128)
    t = SubprocessTransport(0, cfg, fake_clock=True)
    try:
        # the spawn-time probe (one cheap /worker/gauges GET) already
        # completed the handshake, so even the FIRST heavy body — the
        # cold-start ingest in real deployments — rides TKW1
        assert t._peer_binary is True
        # a torn connection renegotiates from the JSON probe
        t._peer_binary = None
        keys = [f"default/pod-{i}" for i in range(200)]
        t._request("POST", "/worker/planned", {"keys": keys})
        assert t._peer_binary is True
        t._request("POST", "/worker/planned", {"keys": keys})
        snap = t.wire_snapshot()
        cell = snap["by_op"]["planned"]
        assert cell["codec"] == "binary"
        # the binary call's compressed frame beat its raw size, so
        # cumulative raw bytes exceed cumulative wire bytes both ways
        assert cell["raw_tx"] > cell["tx"]
        assert cell["raw_rx"] > cell["rx"]
        assert snap["raw_rx"] > snap["rx"]
        assert snap["raw_tx"] > snap["tx"]
    finally:
        t.kill()


@needs_workers
def test_json_default_leaves_wire_untagged():
    """wire_codec: json (the default): no Accept probe, no TKW1
    anywhere, snapshot/cells keep the pre-codec shape."""
    t = None
    from tpukube.sched.shard import SubprocessTransport

    t = SubprocessTransport(0, load_config(env={}), fake_clock=True)
    try:
        t._request("POST", "/worker/planned", {"keys": ["default/x"]})
        assert t._peer_binary is None
        snap = t.wire_snapshot()
        assert set(snap) == {"tx", "rx", "by_op"}
        assert set(snap["by_op"]["planned"]) == {"tx", "rx", "calls"}
    finally:
        t.kill()


def _mixed_workload(c: SimCluster) -> dict[str, tuple[str, tuple]]:
    placements: dict[str, tuple[str, tuple]] = {}

    def put(pod):
        node, alloc = c.schedule(pod)
        placements[alloc.pod_key] = (node,
                                     tuple(sorted(alloc.device_ids)))

    put(c.make_pod("solo-0", tpu=1))
    put(c.make_pod("multi-0", tpu=2))
    grp = PodGroup("pg", min_member=2)
    for i in range(2):
        put(c.make_pod(f"pg-{i}", tpu=1, group=grp, priority=10))
    c.complete_pod("solo-0")
    put(c.make_pod("solo-1", tpu=1))
    return placements


@needs_workers
def test_codec_on_placement_parity_and_bytes_shrink():
    """The tentpole acceptance at test scale: identical placements
    codec-on vs codec-off over 2 real worker daemons, with the binary
    run's wire bill strictly smaller and codec-tagged."""
    results = {}
    wire = {}
    for codec in ("json", "binary"):
        cfg = proc_config(2, TPUKUBE_WIRE_CODEC=codec,
                          TPUKUBE_WIRE_COMPRESS_MIN_BYTES="256")
        with SimCluster(cfg, clock=FakeClock(), in_process=True,
                        slices=two_slices()) as c:
            results[codec] = _mixed_workload(c)
            assert ledger_divergence(c) == []
            wire[codec] = c.extender.wire_totals()
    assert results["binary"] == results["json"]
    assert wire["binary"]["codec"] == "binary"
    assert "codec" not in wire["json"]
    assert wire["binary"]["total"] < wire["json"]["total"]
    assert wire["binary"]["saved"] > 0


@needs_workers
def test_worker_kill_restart_over_binary_transport():
    """Chaos with the codec ON: SIGKILL a worker daemon mid-plane,
    health check marks it dead, warm restart respawns it — and the
    fresh transport re-handshakes from JSON before upgrading (the
    respawned worker might have been older/JSON-only)."""
    clock = FakeClock()
    cfg = proc_config(2, TPUKUBE_WIRE_CODEC="binary",
                      TPUKUBE_SNAPSHOT_AUDIT_RATE="1.0")
    with SimCluster(cfg, clock=clock, in_process=True,
                    slices=two_slices()) as c:
        placed = c.schedule_pending(
            [c.make_pod(f"p{i}", tpu=1) for i in range(8)]
        )
        assert len(placed) == 8
        router = c.extender
        # the live plane really negotiated binary
        assert any("codec" in (rep.transport.wire_snapshot() or {})
                   for rep in router.replicas)
        victim = next(
            idx for idx in (0, 1)
            if router.replicas[idx].transport.summary()["allocs"])
        held = router.replicas[victim].transport.summary()["allocs"]
        router.replicas[victim].transport._proc.kill()
        router.replicas[victim].transport._proc.wait(timeout=10)
        clock.advance(1.0)
        assert router.health_check() == 1
        restored = c.restart_replica(victim)
        assert restored == held
        fresh = router.replicas[victim].transport
        # respawn re-handshakes: fresh transport, no assumed peer
        assert fresh._peer_binary in (None, True)
        # plane still places over the binary transport
        node, _alloc = c.schedule(c.make_pod("after", tpu=1))
        assert node
        assert ledger_divergence(c) == []
        audit = router.audit_stats()
        assert audit["divergences"] == 0

"""C14 deploy manifests: schema sanity + consistency with config defaults.

No cluster exists here (SURVEY.md §9.1); what CAN be verified is that the
YAML is well-formed Kubernetes shape and that every value that must agree
with the code (resource names, ports, socket dir, webhook verbs) does.
"""

import glob
import os

import yaml

from tpukube.core.config import TpuKubeConfig

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "deploy")
CFG = TpuKubeConfig()


def _docs(name: str) -> list[dict]:
    with open(os.path.join(DEPLOY, name)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _all_docs() -> list[dict]:
    out = []
    for path in glob.glob(os.path.join(DEPLOY, "*.yaml")):
        with open(path) as f:
            out.extend(d for d in yaml.safe_load_all(f) if d)
    return out


def test_all_manifests_parse_with_kind_and_metadata():
    docs = _all_docs()
    assert len(docs) >= 9
    for doc in docs:
        assert "kind" in doc and "apiVersion" in doc, doc
        if doc["kind"] != "KubeSchedulerConfiguration":
            assert doc["metadata"].get("name"), doc["kind"]


def test_daemonset_mounts_kubelet_socket_dir():
    ds = next(d for d in _docs("device-plugin-daemonset.yaml")
              if d["kind"] == "DaemonSet")
    spec = ds["spec"]["template"]["spec"]
    host_paths = {
        v["hostPath"]["path"]
        for v in spec["volumes"] if "hostPath" in v
    }
    assert CFG.device_plugin_dir in host_paths
    c = spec["containers"][0]
    assert c["command"] == ["tpukube-plugin"]
    mounts = {m["mountPath"] for m in c["volumeMounts"]}
    assert CFG.device_plugin_dir in mounts
    # real backend on TPU nodes
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert env.get("TPUKUBE_BACKEND") == "real"


def test_extender_service_port_matches_config():
    docs = _docs("extender-deployment.yaml")
    svc = next(d for d in docs if d["kind"] == "Service")
    assert svc["spec"]["ports"][0]["port"] == CFG.extender_port
    dep = next(d for d in docs if d["kind"] == "Deployment")
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["command"] == ["tpukube-extender"]
    assert c["ports"][0]["containerPort"] == CFG.extender_port
    # single replica: in-memory reservation table (deploy/README.md)
    assert dep["spec"]["replicas"] == 1

    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    cfg_doc = yaml.safe_load(cm["data"]["config.yaml"])
    assert cfg_doc["resource_tpu"] == CFG.resource_tpu
    assert cfg_doc["resource_vtpu"] == CFG.resource_vtpu
    # every ConfigMap key must be a real TpuKubeConfig field
    from dataclasses import fields
    known = {f.name for f in fields(CFG)}
    assert set(cfg_doc) <= known


def test_scheduler_config_manages_only_tpu_resources():
    (sched,) = _docs("scheduler-config.yaml")
    assert sched["kind"] == "KubeSchedulerConfiguration"
    (ext,) = sched["extenders"]
    assert ext["filterVerb"] == "filter"
    assert ext["prioritizeVerb"] == "prioritize"
    assert ext["bindVerb"] == "bind"
    assert str(CFG.extender_port) in ext["urlPrefix"]
    managed = {m["name"] for m in ext["managedResources"]}
    assert managed == {CFG.resource_tpu, CFG.resource_vtpu}
    # no nvidia.com/gpu anywhere in the cluster (BASELINE north star)
    assert "nvidia.com/gpu" not in str(_all_docs())


def test_rbac_covers_bindings_and_evictions():
    docs = _docs("rbac.yaml")
    roles = {d["metadata"]["name"]: d for d in docs if d["kind"] == "ClusterRole"}
    ext_rules = roles["tpukube-extender"]["rules"]
    flat = [(r0, v) for r in ext_rules
            for r0 in r["resources"] for v in r["verbs"]]
    assert ("pods/binding", "create") in flat
    assert ("pods", "delete") in flat      # preemption evictions
    # the EvictionExecutor's channel: policy/v1 Eviction subresource POST
    assert ("pods/eviction", "create") in flat
    assert ("nodes", "watch") in flat
    agent_rules = roles["tpukube-node-agent"]["rules"]
    flat_a = [(r0, v) for r in agent_rules
              for r0 in r["resources"] for v in r["verbs"]]
    assert ("nodes", "patch") in flat_a    # node-topology annotation
    # every ServiceAccount referenced by a binding exists
    sas = {d["metadata"]["name"] for d in docs if d["kind"] == "ServiceAccount"}
    for d in docs:
        if d["kind"] == "ClusterRoleBinding":
            for s in d["subjects"]:
                assert s["name"] in sas


def test_gang_job_example_projects_bind_time_env():
    """deploy/gang-job-example.yaml is the user-facing contract for the
    DCN gang env: every TPU_KUBE_GANG_* variable is projected from
    exactly the annotation key the bind effector mints
    (codec.GANG_ENV_TO_ANNO), and the pod-group annotations decode to a
    valid gang spec."""
    from tpukube.core import codec

    (job,) = _docs("gang-job-example.yaml")
    assert job["kind"] == "Job"
    tmpl = job["spec"]["template"]

    # gang identity annotations decode through the real codec
    group = codec.pod_group_from_annotations(
        tmpl["metadata"]["annotations"]
    )
    assert group is not None
    assert group.min_member == job["spec"]["parallelism"]
    assert group.allow_dcn is True

    (container,) = tmpl["spec"]["containers"]
    assert container["resources"]["requests"][CFG.resource_tpu]
    projected = {}
    for env in container["env"]:
        path = env.get("valueFrom", {}).get("fieldRef", {}).get(
            "fieldPath", ""
        )
        if path.startswith("metadata.annotations['tpu.qiniu.com/"):
            projected[env["name"]] = path.split("'")[1]
    assert projected == codec.GANG_ENV_TO_ANNO


def test_extender_channel_is_secure_by_default():
    """VERDICT round-4 task 3: the scheduler->extender channel ships
    mTLS — enableHTTPS with a client cert in scheduler-config, the
    serving cert + client CA mounted and required in the Deployment."""
    (sched,) = _docs("scheduler-config.yaml")
    (ext,) = sched["extenders"]
    assert ext["enableHTTPS"] is True
    assert ext["urlPrefix"].startswith("https://")
    tls = ext["tlsConfig"]
    assert {"certFile", "keyFile", "caFile"} <= set(tls)

    docs = _docs("extender-deployment.yaml")
    (deploy,) = [d for d in docs if d["kind"] == "Deployment"]
    (container,) = deploy["spec"]["template"]["spec"]["containers"]
    args = container["args"]
    assert any(a.startswith("--tls-cert=") for a in args)
    assert any(a.startswith("--tls-key=") for a in args)
    assert any(a.startswith("--tls-client-ca=") for a in args)
    # kubelet probes cannot present client certs: with mTLS on the main
    # port, probes MUST target the plain probe listener
    assert "--probe-port=12346" in args
    port_names = {p["name"]: p["containerPort"]
                  for p in container["ports"]}
    assert port_names == {"https": 12345, "probe": 12346}
    for probe in ("readinessProbe", "livenessProbe"):
        assert container[probe]["httpGet"]["port"] == "probe"
        assert container[probe]["httpGet"].get("scheme", "HTTP") == "HTTP"
    mounts = {m["name"] for m in container["volumeMounts"]}
    assert "tpukube-extender-tls" in mounts
    vols = {v["name"]: v for v in deploy["spec"]["template"]["spec"]["volumes"]}
    assert vols["tpukube-extender-tls"]["secret"]["secretName"]

import pytest

from tpukube.core.mesh import Box, MeshSpec, factor_shapes
from tpukube.core.types import TopologyCoord


def test_mesh_counts():
    m = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))
    assert m.num_chips == 64
    assert m.chips_per_host == 4
    assert m.num_hosts == 16
    assert m.host_grid == (2, 2, 4)


def test_mesh_rejects_nondividing_host_block():
    with pytest.raises(ValueError):
        MeshSpec(dims=(4, 4, 3), host_block=(2, 2, 2))


def test_linearize_roundtrip():
    m = MeshSpec(dims=(4, 2, 3), host_block=(1, 1, 1))
    seen = set()
    for c in m.all_coords():
        i = m.linearize(c)
        assert m.delinearize(i) == c
        seen.add(i)
    assert seen == set(range(m.num_chips))


def test_host_partition_covers_mesh_exactly():
    m = MeshSpec(dims=(4, 4, 2), host_block=(2, 2, 1))
    all_from_hosts = []
    for h in m.all_hosts():
        coords = m.coords_of_host(h)
        assert len(coords) == m.chips_per_host
        for c in coords:
            assert m.host_of(c) == h
        all_from_hosts.extend(coords)
    assert len(all_from_hosts) == m.num_chips
    assert set(all_from_hosts) == set(m.all_coords())


def test_host_origin_rejects_bad_names():
    m = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    with pytest.raises(ValueError):
        m.host_origin("host-9-0-0")
    with pytest.raises(ValueError):
        m.host_origin("gpu-0-0-0")


def test_neighbors_interior_and_edge():
    m = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))
    assert len(m.neighbors(TopologyCoord(1, 1, 1))) == 6
    corner = m.neighbors(TopologyCoord(0, 0, 0))
    assert len(corner) == 3
    assert set(corner) == {
        TopologyCoord(1, 0, 0),
        TopologyCoord(0, 1, 0),
        TopologyCoord(0, 0, 1),
    }


def test_neighbors_torus_wraps():
    m = MeshSpec(dims=(4, 4, 1), host_block=(1, 1, 1), torus=(True, True, False))
    nb = m.neighbors(TopologyCoord(0, 0, 0))
    assert TopologyCoord(3, 0, 0) in nb and TopologyCoord(0, 3, 0) in nb
    assert len(nb) == 4


def test_neighbors_dim1_axis_skipped():
    m = MeshSpec(dims=(2, 1, 1), host_block=(1, 1, 1), torus=(True, True, True))
    # wraparound on a length-2 axis must not duplicate the single neighbor
    assert m.neighbors(TopologyCoord(0, 0, 0)) == [TopologyCoord(1, 0, 0)]


def test_box_coords_and_containment():
    b = Box(TopologyCoord(1, 1, 0), (2, 2, 1))
    cs = list(b.coords())
    assert len(cs) == b.size == 4
    assert b.contains(TopologyCoord(2, 2, 0))
    assert not b.contains(TopologyCoord(3, 1, 0))
    m = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    assert b.fits_in(m)
    assert not Box(TopologyCoord(3, 3, 0), (2, 1, 1)).fits_in(m)


def test_factor_shapes_prefers_compact():
    shapes = factor_shapes(16, (4, 4, 4))

    def surface(s):
        a, b, c = s
        return 2 * (a * b + b * c + a * c)

    # first shape must be one of the minimum-surface boxes: the (4,2,2)
    # family (surface 40) beats the (4,4,1) family (surface 48)
    assert surface(shapes[0]) == min(surface(s) for s in shapes) == 40
    # and the ordering is monotone in surface area
    assert [surface(s) for s in shapes] == sorted(surface(s) for s in shapes)
    assert all(a * b * c == 16 for a, b, c in shapes)
    # nothing exceeds the mesh dims
    assert all(a <= 4 and b <= 4 and c <= 4 for a, b, c in shapes)


def test_factor_shapes_respects_mesh_limits():
    shapes = factor_shapes(8, (8, 1, 1))
    assert shapes == [(8, 1, 1)]
    assert factor_shapes(16, (2, 2, 2)) == []


def test_mesh_json_roundtrip():
    m = MeshSpec(dims=(8, 8, 2), host_block=(2, 2, 1), torus=(True, False, False))
    assert MeshSpec.from_json(m.to_json()) == m

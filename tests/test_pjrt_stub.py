"""Executes the real backend's PJRT enumeration path (tpuinfo.cpp
enumerate_pjrt) against a stub PJRT plugin — no TPU hardware required.

The stub (tests/native/pjrt_stub.cpp) is compiled here and handed to the
real backend as its ``libtpu=``; every scenario the enumeration must
survive — happy path, non-addressable peers, missing coords, absent
MemoryStats, too-old plugin struct, major-version skew, busy chip — is an
env knob on the stub. This is the test the PJRT code path runs under in
CI every round (previously it had never executed anywhere)."""

import glob
import os
import subprocess

import pytest

from tpukube.native import TpuInfo
from tpukube.native.tpuinfo import TpuInfoError

HERE = os.path.dirname(os.path.abspath(__file__))
STUB_SRC = os.path.join(HERE, "native", "pjrt_stub.cpp")

_STUB_KNOBS = [
    "PJRT_STUB_DEVICES", "PJRT_STUB_CORES", "PJRT_STUB_GRID_X",
    "PJRT_STUB_HBM", "PJRT_STUB_KIND", "PJRT_STUB_REMOTE",
    "PJRT_STUB_NO_COORDS", "PJRT_STUB_NO_MEMSTATS", "PJRT_STUB_OLD_STRUCT",
    "PJRT_STUB_BAD_MAJOR", "PJRT_STUB_FAIL_CLIENT", "PJRT_STUB_PARTIAL_COORDS",
    "PJRT_STUB_WRAP",
]


def _pjrt_include() -> str | None:
    for pat in (
        "/opt/venv/lib/python*/site-packages/tensorflow/include",
        "/usr/lib/python*/site-packages/tensorflow/include",
    ):
        hits = glob.glob(pat)
        if hits:
            return hits[0]
    return None


@pytest.fixture(scope="session")
def stub_so(tmp_path_factory):
    inc = _pjrt_include()
    if inc is None:
        pytest.skip("no PJRT C API header on this machine")
    out = tmp_path_factory.mktemp("pjrt_stub") / "libpjrtstub.so"
    subprocess.run(
        ["g++", "-O1", "-Wall", "-Werror", "-fPIC", "-shared", "-std=c++17",
         f"-I{inc}", "-o", str(out), STUB_SRC],
        check=True, capture_output=True, text=True,
    )
    return str(out)


@pytest.fixture(autouse=True)
def clean_stub_env(monkeypatch):
    for k in _STUB_KNOBS:
        monkeypatch.delenv(k, raising=False)
    yield


def test_pjrt_enumeration_happy_path(stub_so, monkeypatch):
    """8 cores / 2 per chip -> 4 chips on a 2x2 grid, ids <kind>-<min id>,
    HBM from MemoryStats, and source()=="pjrt" (runtime introspection, not
    the table fallback)."""
    monkeypatch.setenv("PJRT_STUB_HBM", str(20 << 30))
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source() == "pjrt"
        chips = ti.chips()
        assert len(chips) == 4
        # chips are coord-sorted (x,y,z lexicographic); device ids 0+1
        # share chip (0,0,0), 2+3 share (1,0,0), ...
        assert [c.chip_id for c in chips] == [
            "stubtpu-0", "stubtpu-4", "stubtpu-2", "stubtpu-6",
        ]
        assert [(c.coord.x, c.coord.y, c.coord.z) for c in chips] == [
            (0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0),
        ]
        assert all(c.num_cores == 2 for c in chips)
        assert all(c.hbm_bytes == 20 << 30 for c in chips)
        mesh = ti.mesh()
        assert mesh.dims == (2, 2, 1)
        assert mesh.host_block == (2, 2, 1)


def test_pjrt_skips_non_addressable_devices(stub_so, monkeypatch):
    """Another host's devices (non-addressable) are not this node's
    inventory."""
    monkeypatch.setenv("PJRT_STUB_DEVICES", "4")
    monkeypatch.setenv("PJRT_STUB_REMOTE", "4")
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source() == "pjrt"
        assert ti.chip_count() == 2  # 4 local cores / 2 per chip


def test_pjrt_missing_coords_mints_fallback_ids(stub_so, monkeypatch):
    """A plugin without the coords attribute still enumerates: each device
    gets a distinct synthetic (i,0,0) coord."""
    monkeypatch.setenv("PJRT_STUB_DEVICES", "3")
    monkeypatch.setenv("PJRT_STUB_CORES", "1")
    monkeypatch.setenv("PJRT_STUB_NO_COORDS", "1")
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source() == "pjrt"
        chips = ti.chips()
        assert len(chips) == 3
        assert [(c.coord.x, c.coord.y, c.coord.z) for c in chips] == [
            (0, 0, 0), (1, 0, 0), (2, 0, 0),
        ]
        assert ti.mesh().dims == (3, 1, 1)


def test_pjrt_absent_memstats_uses_gen_table_hbm(stub_so, monkeypatch):
    """An old plugin without PJRT_Device_MemoryStats still enumerates via
    PJRT; HBM comes from the generation table (gen=v4 -> 32 GiB)."""
    monkeypatch.setenv("PJRT_STUB_NO_MEMSTATS", "1")
    with TpuInfo("real", f"libtpu={stub_so}\ngen=v4") as ti:
        assert ti.source() == "pjrt"
        assert all(c.hbm_bytes == 32 << 30 for c in ti.chips())


def test_pjrt_old_struct_falls_back_to_table(stub_so, monkeypatch):
    """A plugin whose PJRT_Api predates the required entry points is
    rejected cleanly: table fallback, with the reason in source()."""
    monkeypatch.setenv("PJRT_STUB_OLD_STRUCT", "1")
    with TpuInfo("real", f"libtpu={stub_so}\ngen=v5e\nchips=2") as ti:
        assert ti.source().startswith("table (")
        assert "too old" in ti.source()
        chips = ti.chips()
        assert len(chips) == 2
        assert chips[0].chip_id.startswith("local-v5e-")
        assert chips[0].hbm_bytes == 16 << 30


def test_pjrt_major_version_skew_falls_back(stub_so, monkeypatch):
    monkeypatch.setenv("PJRT_STUB_BAD_MAJOR", "1")
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source().startswith("table (")
        assert "major version" in ti.source()


def test_pjrt_busy_chip_falls_back(stub_so, monkeypatch):
    """Client_Create failing (chip owned by another process — this
    machine's actual situation with the tunnel) degrades to the table."""
    monkeypatch.setenv("PJRT_STUB_FAIL_CLIENT", "1")
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source().startswith("table (Client_Create:")
        assert "busy" in ti.source()


def test_pjrt_device_manager_over_stub(stub_so, monkeypatch):
    """The full device-manager path over PJRT enumeration: discovery,
    device minting, and node_info all ride the runtime-reported chips."""
    from tpukube.core.config import load_config
    from tpukube.device import TpuDeviceManager

    cfg = load_config(env={
        "TPUKUBE_BACKEND": "real",
        "TPUKUBE_LIBTPU_PATH": stub_so,
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm:
        info = dm.node_info()
        assert len(info.chips) == 4
        assert {c.chip_id for c in info.chips} == {
            "stubtpu-0", "stubtpu-2", "stubtpu-4", "stubtpu-6",
        }
        ids = [d for d, _ in dm.device_list()]
        assert ids == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]


def test_real_backend_missing_libtpu_still_errors(tmp_path):
    """The liveness gate is untouched: a bogus libtpu path fails init."""
    bogus = tmp_path / "not_a_lib.so"
    bogus.write_bytes(b"\x7fELF-not-really")
    with pytest.raises(TpuInfoError, match="cannot load libtpu"):
        TpuInfo("real", f"libtpu={bogus}")


def test_pjrt_wrap_attribute_sets_torus(stub_so, monkeypatch):
    """When the runtime exposes per-axis wrap flags (the "wrap" int64[3]
    attribute), the mesh reports a real torus instead of the bounding-box
    default."""
    monkeypatch.setenv("PJRT_STUB_WRAP", "1,1,0")
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source() == "pjrt"
        assert ti.mesh().torus == (True, True, False)


def test_pjrt_partial_coords_rejected_to_table(stub_so, monkeypatch):
    """A plugin reporting coords for only SOME devices would let synthetic
    fallback coords collide with real ones (corrupting core counts/ids):
    enumeration must reject and fall back to the honest table."""
    monkeypatch.setenv("PJRT_STUB_PARTIAL_COORDS", "1")
    with TpuInfo("real", f"libtpu={stub_so}\nchips=1") as ti:
        assert ti.source().startswith("table (")
        assert "collide" in ti.source()


def test_real_torus_config_override(stub_so, monkeypatch):
    """Operator-configured torus flags apply to real nodes when the
    runtime reported none; a runtime-reported wrap always wins."""
    from tpukube.core.config import load_config
    from tpukube.device import TpuDeviceManager

    cfg = load_config(env={
        "TPUKUBE_BACKEND": "real",
        "TPUKUBE_LIBTPU_PATH": stub_so,
        "TPUKUBE_REAL_TORUS": "1,1,0",
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm:
        assert dm.mesh.torus == (True, True, False)

    monkeypatch.setenv("PJRT_STUB_WRAP", "0,0,1")  # runtime knows better
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm:
        assert dm.mesh.torus == (False, False, True)


# -- health canary (SURVEY §4.4 real-mode, previously unreachable) ----------

def test_probe_client_mode_flips_health(stub_so, monkeypatch):
    """probe=client: a failing canary enumeration marks every chip
    Unhealthy; a passing one restores them."""
    with TpuInfo("real", f"libtpu={stub_so}\nprobe=client") as ti:
        assert ti.source() == "pjrt"
        assert ti.probe() is True
        assert all(c.health.value == "Healthy" for c in ti.chips())

        monkeypatch.setenv("PJRT_STUB_FAIL_CLIENT", "1")
        assert ti.probe() is False
        assert all(c.health.value == "Unhealthy" for c in ti.chips())

        monkeypatch.delenv("PJRT_STUB_FAIL_CLIENT")
        assert ti.probe() is True
        assert all(c.health.value == "Healthy" for c in ti.chips())


def test_probe_default_liveness_no_false_alarm(stub_so, monkeypatch):
    """The DEFAULT probe is liveness (libtpu loadable): a busy chip —
    client create failing while a workload holds it — must NOT flip
    health (the single-owner false-alarm the client mode documents)."""
    with TpuInfo("real", f"libtpu={stub_so}") as ti:
        assert ti.source() == "pjrt"
        monkeypatch.setenv("PJRT_STUB_FAIL_CLIENT", "1")  # workload arrived
        assert ti.probe() is True
        assert all(c.health.value == "Healthy" for c in ti.chips())


def test_probe_liveness_does_not_leak_handles(stub_so):
    """round-4 advisor low: every liveness probe dlopens RTLD_NOLOAD and
    must dlclose the hit — a daemon polls this every few seconds, so a
    leaked reference per poll grows libtpu's refcount without bound.
    Observable (in a FRESH process — every in-process init retains one
    reference by design): after N probes + shutdown, consuming our
    check-open plus the one intentionally-retained init reference must
    fully unmap the image; any leaked probe reference keeps it mapped."""
    import sys

    script = """
import ctypes, sys
stub = sys.argv[1]
sys.path.insert(0, sys.argv[2])
from tpukube.native import TpuInfo
with TpuInfo("real", f"libtpu={stub}") as ti:
    for _ in range(32):
        assert ti.probe() is True
def mapped():
    return stub in open("/proc/self/maps").read()
assert mapped(), "retained init handle should keep the image mapped"
libdl = ctypes.CDLL(None)
libdl.dlopen.restype = ctypes.c_void_p
libdl.dlopen.argtypes = [ctypes.c_char_p, ctypes.c_int]
libdl.dlclose.argtypes = [ctypes.c_void_p]
h = libdl.dlopen(stub.encode(), 0x1 | 0x4)  # RTLD_LAZY | RTLD_NOLOAD
assert h
libdl.dlclose(ctypes.c_void_p(h))  # our check-open
libdl.dlclose(ctypes.c_void_p(h))  # the retained init reference
assert not mapped(), "probe() leaked dlopen handles"
"""
    repo_root = os.path.dirname(HERE)
    subprocess.run(
        [sys.executable, "-c", script, stub_so, repo_root],
        check=True, capture_output=True, text=True,
    )


def test_probe_failure_shrinks_allocatable_via_listandwatch(
    stub_so, tmp_path, monkeypatch
):
    """VERDICT round-2 task 3's 'done' bar: a failing probe on a
    real-backend plugin server shrinks the kubelet's allocatable through
    the live ListAndWatch stream — SURVEY §4.4 end to end without
    hardware."""
    from tpukube.core.config import load_config
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer, FakeKubelet
    from tpukube.plugin.server import HealthWatcher

    cfg = load_config(env={
        "TPUKUBE_BACKEND": "real",
        "TPUKUBE_LIBTPU_PATH": stub_so,
        "TPUKUBE_PROBE_MODE": "client",
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm, \
            DevicePluginServer(cfg, dm) as server, \
            FakeKubelet(str(tmp_path)) as kubelet:
        server.register_with_kubelet()
        devs = kubelet.wait_for_devices(server.resource_name, 4)
        assert len(devs) == 4

        watcher = HealthWatcher(dm, server, poll_seconds=999)
        watcher._last = dm.health_snapshot()  # what start() does
        assert watcher.check_once() is False  # healthy, no transition

        monkeypatch.setenv("PJRT_STUB_FAIL_CLIENT", "1")  # chip dies
        assert watcher.check_once() is True
        for d in devs:
            kubelet.wait_for_health(server.resource_name, d, "Unhealthy")
        assert watcher.transitions == 1

        monkeypatch.delenv("PJRT_STUB_FAIL_CLIENT")  # chip recovers
        assert watcher.check_once() is True
        for d in devs:
            kubelet.wait_for_health(server.resource_name, d, "Healthy")


def test_node_info_carries_inventory_source(stub_so):
    """The annotation channel surfaces WHERE the inventory came from, so
    operators can spot table-fallback nodes cluster-wide."""
    from tpukube.core import codec
    from tpukube.core.config import load_config
    from tpukube.device import TpuDeviceManager

    cfg = load_config(env={
        "TPUKUBE_BACKEND": "real",
        "TPUKUBE_LIBTPU_PATH": stub_so,
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as dm:
        info = dm.node_info()
        assert info.source == "pjrt"
        anno = codec.annotate_node(info, dm.mesh)
        decoded, _ = codec.decode_node_topology(
            anno[codec.ANNO_NODE_TOPOLOGY]
        )
        assert decoded.source == "pjrt"

"""Extender surface authentication (VERDICT round-4 task 3).

/bind mutates the ledger and executes preemption; /state and /trace
disclose the whole cluster's placement — neither may answer anonymous
callers. Two modes, both tested against the REAL serving path
(make_app + the same TCPSite configuration cli.main_extender builds):

  * bearer token — application-level gate on every route except
    /healthz (kubelet probes) and /metrics (Prometheus).
  * mTLS — the TLS layer itself rejects peers without a CA-signed
    client certificate (what stock kube-scheduler's extender tlsConfig
    speaks).
"""

import json
import ssl
import subprocess
import urllib.error
import urllib.request

import pytest

from tpukube.core.config import load_config
from tpukube.sched.extender import Extender, make_app
from tpukube.sim.harness import _AppThread, _free_port

CFG_ENV = {
    "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
    "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
}


def _get(url, token=None, ctx=None):
    req = urllib.request.Request(url)
    if token is not None:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
        return r.status, r.read()


def test_bearer_token_gates_all_but_probe_routes():
    ext = Extender(load_config(env=CFG_ENV))
    port = _free_port()
    app = _AppThread(make_app(ext, auth_token="s3cret"), "127.0.0.1", port)
    app.start()
    base = f"http://127.0.0.1:{port}"
    try:
        # probes and scrapes stay open (read-only, non-disclosing)
        assert _get(f"{base}/healthz")[0] == 200
        assert _get(f"{base}/metrics")[0] == 200

        # disclosure + mutation routes: anonymous -> 401
        for path in ("/state/topology", "/state/allocs", "/state/gangs",
                     "/trace"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{base}{path}")
            assert e.value.code == 401
            assert e.value.headers.get("WWW-Authenticate") == "Bearer"
        body = json.dumps({"Pod": {"metadata": {"name": "p"}},
                           "NodeNames": []}).encode()
        req = urllib.request.Request(
            f"{base}/filter", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 401

        # wrong token -> 401; right token -> accepted
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{base}/state/topology", token="wrong")
        assert e.value.code == 401
        status, raw = _get(f"{base}/state/topology", token="s3cret")
        assert status == 200 and json.loads(raw)["chips_total"] == 0
        req.add_header("Authorization", "Bearer s3cret")
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
    finally:
        app.stop()


@pytest.fixture(scope="module")
def tls_pki(tmp_path_factory):
    """A tiny CA + server cert (CN localhost, SAN 127.0.0.1) + client
    cert, as cert-manager would issue into the deploy/ secrets."""
    d = tmp_path_factory.mktemp("pki")

    def o(*cmd):
        subprocess.run(cmd, check=True, capture_output=True, cwd=d)

    o("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
      "-keyout", "ca.key", "-out", "ca.crt", "-days", "2",
      "-subj", "/CN=tpukube-test-ca")
    for name, cn, ext in (
        ("server", "localhost", "subjectAltName=IP:127.0.0.1,DNS:localhost"),
        ("client", "kube-scheduler", "extendedKeyUsage=clientAuth"),
    ):
        (d / f"{name}.ext").write_text(ext + "\n")
        o("openssl", "req", "-newkey", "rsa:2048", "-nodes",
          "-keyout", f"{name}.key", "-out", f"{name}.csr",
          "-subj", f"/CN={cn}")
        o("openssl", "x509", "-req", "-in", f"{name}.csr",
          "-CA", "ca.crt", "-CAkey", "ca.key", "-CAcreateserial",
          "-out", f"{name}.crt", "-days", "2",
          "-extfile", f"{name}.ext")
    return d


def test_mtls_requires_ca_signed_client_cert(tls_pki):
    """The mTLS half of the deploy/ default: the extender serves HTTPS
    and the handshake itself rejects clients without a CA-signed cert —
    exactly the SSLContext cli.main_extender builds from
    --tls-cert/--tls-key/--tls-client-ca."""
    d = tls_pki
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(str(d / "server.crt"), str(d / "server.key"))
    server_ctx.load_verify_locations(str(d / "ca.crt"))
    server_ctx.verify_mode = ssl.CERT_REQUIRED

    ext = Extender(load_config(env=CFG_ENV))
    port = _free_port()
    app = _AppThread(make_app(ext), "127.0.0.1", port,
                     ssl_context=server_ctx)
    app.start()
    base = f"https://127.0.0.1:{port}"
    try:
        # kube-scheduler's shape: CA-pinned server + client cert -> 200
        ok_ctx = ssl.create_default_context(cafile=str(d / "ca.crt"))
        ok_ctx.load_cert_chain(str(d / "client.crt"), str(d / "client.key"))
        status, raw = _get(f"{base}/healthz", ctx=ok_ctx)
        assert status == 200 and json.loads(raw)["ok"] is True

        # no client cert: rejected at the TLS layer — nothing is served.
        # (TLS1.3 surfaces this as an alert OR a bare connection close
        # depending on timing, so accept any OSError: URLError,
        # SSLError, and RemoteDisconnected all are; what matters is no
        # HTTP response ever arrives.)
        anon_ctx = ssl.create_default_context(cafile=str(d / "ca.crt"))
        with pytest.raises(OSError):
            _get(f"{base}/state/topology", ctx=anon_ctx)

        # a self-signed (not CA-signed) client cert also fails
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", "rogue.key", "-out", "rogue.crt", "-days", "2",
             "-subj", "/CN=rogue"],
            check=True, capture_output=True, cwd=d)
        rogue_ctx = ssl.create_default_context(cafile=str(d / "ca.crt"))
        rogue_ctx.load_cert_chain(str(d / "rogue.crt"), str(d / "rogue.key"))
        with pytest.raises(OSError):
            _get(f"{base}/state/topology", ctx=rogue_ctx)
    finally:
        app.stop()


def test_bearer_rejects_non_ascii_header_with_401():
    """A crafted non-ASCII Authorization header must get a 401, not a
    500 (str-mode hmac.compare_digest raises on non-ASCII)."""
    ext = Extender(load_config(env=CFG_ENV))
    port = _free_port()
    app = _AppThread(make_app(ext, auth_token="s3cret"), "127.0.0.1", port)
    app.start()
    try:
        req = urllib.request.Request(f"http://127.0.0.1:{port}/trace")
        req.add_header("Authorization", "Bearer tüken")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 401
    finally:
        app.stop()


def test_probe_listener_serves_only_healthz_and_metrics():
    """The mTLS deployment's second listener (--probe-port): kubelet
    probes and Prometheus get /healthz + /metrics over plain HTTP, and
    NOTHING else leaks onto that port."""
    from tpukube.sched.extender import make_probe_app, run_probe_server

    ext = Extender(load_config(env=CFG_ENV))
    port = _free_port()
    stop = run_probe_server(make_probe_app(ext), "127.0.0.1", port)
    base = f"http://127.0.0.1:{port}"
    try:
        status, raw = _get(f"{base}/healthz")
        assert status == 200 and json.loads(raw)["ok"] is True
        status, raw = _get(f"{base}/metrics")
        assert status == 200 and b"tpu_chip_utilization_percent" in raw
        for path in ("/state/topology", "/trace", "/bind"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{base}{path}")
            assert e.value.code == 404, path
    finally:
        stop()


def test_extender_cli_flag_validation():
    """Mismatched TLS flag combinations are configuration errors, caught
    before any socket opens."""
    from tpukube.cli import main_extender

    with pytest.raises(SystemExit):
        main_extender(["--tls-cert", "/tmp/x.pem"])  # key missing
    with pytest.raises(SystemExit):
        main_extender(["--tls-client-ca", "/tmp/ca.pem"])  # no serving cert

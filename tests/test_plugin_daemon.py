"""tpukube-plugin as a real daemon process: the full SURVEY.md §4.1 startup
sequence (discover → annotate → register with kubelet → serve) driven from
outside, exactly as a kubelet on a TPU node would see it."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpukube.core import codec
from tpukube.plugin.fake_kubelet import FakeKubelet


@pytest.fixture
def plugin_dir(tmp_path):
    d = tmp_path / "device-plugins"
    d.mkdir()
    return str(d)


def test_plugin_daemon_full_lifecycle(plugin_dir, tmp_path):
    anno_path = str(tmp_path / "node-annotation.json")
    with FakeKubelet(plugin_dir) as kubelet:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpukube.cli", "plugin",
             "--metrics-port", "0", "--annotation-out", anno_path],
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "TPUKUBE_BACKEND": "sim",
                "TPUKUBE_DEVICE_PLUGIN_DIR": plugin_dir,
                "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
                "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
            },
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            # daemon registers itself and streams its device list
            kubelet.wait_for_devices("qiniu.com/tpu", 4, timeout=30)
            assert kubelet.allocatable("qiniu.com/tpu") == 4

            # node-topology annotation emitted for the apiserver syncer
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not os.path.exists(anno_path):
                time.sleep(0.1)
            with open(anno_path) as f:
                anno = json.load(f)
            node, mesh = codec.node_from_annotations("host-0-0-0", anno)
            assert mesh.dims == (2, 2, 1)
            assert len(node.chips) == 4

            # Allocate through the daemon's socket returns the JAX env
            env = kubelet.allocate("qiniu.com/tpu", ["tpu-1"])
            assert env["TPU_VISIBLE_DEVICES"] == "1"
            assert "TPU_KUBE_CHIP_COORDS" in env

            # clean shutdown on SIGTERM
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

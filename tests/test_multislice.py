"""Multi-slice (ICI + DCN) cluster tests.

SURVEY.md §3 "distributed communication backend": ICI is intra-slice, DCN is
inter-slice. A multi-slice cluster therefore has slice-local coordinate
spaces; gangs (ICI-contiguous by definition) never span slices, and the
extender's slice choice bin-packs so empty slices stay whole for big gangs.
"""

import pytest

from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup, TopologyCoord
from tpukube.sim import SimCluster

M22 = MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))
M44 = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))


def _cfg(**extra):
    env = {"TPUKUBE_RESERVATION_TTL_SECONDS": "30"}
    env.update(extra)
    return load_config(env=env)


def two_slices():
    return SimCluster(_cfg(), slices={"slice-a": M44, "slice-b": M44})


def test_same_coords_in_different_slices_dont_conflict():
    with SimCluster(_cfg(), slices={"slice-a": M22, "slice-b": M22}) as c:
        # 8 chips total across two 4-chip slices; all 8 must be placeable
        # even though every coord exists twice (once per slice)
        nodes = [c.schedule(c.make_pod(f"p-{i}", tpu=1))[0] for i in range(8)]
        assert len({n for n in nodes}) == 2  # one node per slice here
        assert c.utilization() == 1.0
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule(c.make_pod("p-8", tpu=1))


def test_gang_never_spans_slices():
    with SimCluster(_cfg(), slices={"slice-a": M22, "slice-b": M22}) as c:
        # 4 free chips per slice; an 8-pod gang would need both => must fail
        group = PodGroup("big", min_member=8)
        with pytest.raises(RuntimeError, match="no contiguous"):
            c.schedule(c.make_pod("g-0", tpu=1, group=group))
        # a 4-pod gang fits inside one slice and commits
        small = PodGroup("small", min_member=4)
        nodes = {
            c.schedule(c.make_pod(f"s-{i}", tpu=1, group=small))[0]
            for i in range(4)
        }
        res = c.extender.gang.reservation("default", "small")
        assert res.committed
        assert len(nodes) == 1  # one host block == one slice here


def test_gang_slice_choice_binpacks():
    with two_slices() as c:
        # preload slice-b with 4 pods so it is fuller
        for i in range(4):
            node, _ = c.schedule(c.make_pod(f"pre-{i}", tpu=1))
        # all preloads land on ONE slice (binpack/topology scoring is
        # deterministic); find which
        preload_slice = {c.extender.state.slice_of_node(
            c.pods[f"default/pre-{i}"]["spec"]["nodeName"])
            for i in range(4)}
        assert len(preload_slice) == 1
        loaded = preload_slice.pop()
        # a 8-pod gang fits in both slices; bin-pack must choose the fuller
        group = PodGroup("packed", min_member=8)
        c.schedule(c.make_pod("g-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "packed")
        assert res.slice_id == loaded


def test_link_fault_is_slice_local():
    with two_slices() as c:
        # the same link coords are downed in slice-a only
        c.inject_link_fault((1, 1, 0), (2, 1, 0), slice_id="slice-a")
        group = PodGroup("whole", min_member=16)  # needs a full 4x4 slice
        c.schedule(c.make_pod("w-0", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "whole")
        assert res.slice_id == "slice-b"


def test_preemption_plans_per_slice():
    with two_slices() as c:
        # fill BOTH slices with burst pods (priority 1)
        pods = [c.schedule(c.make_pod(f"b-{i}", tpu=1, priority=1))
                for i in range(32)]
        assert c.utilization() == 1.0
        # a priority-100 16-pod gang must evict exactly one slice's worth
        group = PodGroup("train", min_member=16)
        c.schedule(c.make_pod("t-0", tpu=1, group=group, priority=100))
        res = c.extender.gang.reservation("default", "train")
        assert res.slice_id in ("slice-a", "slice-b")
        evicted = c.drain_evictions()
        # 16 single-chip victims, all in the reservation's slice
        assert c.extender.preemptions == 16
        for i in range(1, 16):
            c.schedule(c.make_pod(f"t-{i}", tpu=1, group=group, priority=100))
        assert res.committed


def test_snapshot_reports_slices():
    with two_slices() as c:
        c.schedule(c.make_pod("p-0", tpu=1))
        c.inject_link_fault((0, 0, 0), (1, 0, 0), slice_id="slice-b")
        c.schedule(c.make_pod("p-1", tpu=1))  # re-ingest annotations
        topo = c.extender.topology_snapshot()
        assert topo["mesh_dims"] is None  # multi-slice: no single dims
        assert [s["id"] for s in topo["slices"]] == ["slice-a", "slice-b"]
        by_id = {s["id"]: s for s in topo["slices"]}
        assert by_id["slice-b"]["links_down"] == [[[0, 0, 0], [1, 0, 0]]]
        assert by_id["slice-a"]["links_down"] == []
        assert topo["chips_total"] == 32
        slices_of_nodes = {n["slice"] for n in topo["nodes"]}
        assert slices_of_nodes == {"slice-a", "slice-b"}


def test_restart_rebuild_restores_gang_slice():
    from tpukube.core import codec
    from tpukube.sched.extender import Extender

    with two_slices() as c:
        group = PodGroup("job", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"j-{i}", tpu=1, group=group))
        old = c.extender.gang.reservation("default", "job")
        assert old.committed
        # fresh extender, rebuilt from node + pod annotations only
        ext = Extender(c.config)
        for obj in c.node_objects():
            ext.state.upsert_node(
                obj["metadata"]["name"], obj["metadata"]["annotations"]
            )
        ext.rebuild_from_pods(
            [p["metadata"]["annotations"] for p in c.pods.values()]
        )
        res = ext.gang.reservation("default", "job")
        assert res is not None and res.committed
        assert res.slice_id == old.slice_id


def test_allocation_executes_on_prefixed_node():
    """The real device-plugin stack runs for a slice-prefixed node name
    (free-form host label + explicit origin in the native sim spec)."""
    with SimCluster(_cfg(), slices={"slice-a": M22, "slice-b": M22}) as c:
        node, alloc = c.schedule(c.make_pod("p-0", tpu=1))
        env = c.execute_allocation(alloc)
        assert env["TPU_KUBE_HOST"] == node
        assert env["TPU_KUBE_SLICE_ID"] == c.extender.state.slice_of_node(node)
        got = env["TPU_KUBE_CHIP_COORDS"].split(";")
        assert len(got) == 1


def test_mixed_mesh_sizes_across_slices():
    with SimCluster(_cfg(), slices={"small": M22, "large": M44}) as c:
        # a 16-pod gang only fits in the large slice
        group = PodGroup("big", min_member=16)
        for i in range(16):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
        res = c.extender.gang.reservation("default", "big")
        assert res.committed and res.slice_id == "large"
        # the small slice still serves singles; 4 + 16 chips all allocated
        for i in range(4):
            c.schedule(c.make_pod(f"s-{i}", tpu=1))
        assert c.utilization() == 1.0
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule(c.make_pod("overflow", tpu=1))


def test_vtpu_nodes_in_multislice_cluster():
    """Fractional vTPU sharing composes with multi-slice: a vTPU node in
    each slice serves shares; whole-chip pods avoid them; utilization
    aggregates correctly."""
    vtpu = {"slice-a-host-0-0-0", "slice-b-host-0-0-0"}
    with SimCluster(_cfg(), slices={"slice-a": M22, "slice-b": M22},
                    vtpu_nodes=vtpu, vtpu_shares=2) as c:
        # 4 shares per vTPU node (2 chips... M22 = 4 chips -> 8 shares)
        nodes = set()
        for i in range(4):
            n, a = c.schedule(c.make_pod(f"v-{i}", vtpu=1))
            nodes.add(n)
            assert n in vtpu
        # shares pack onto already-used chips first, within both slices
        assert len(nodes) <= 2


def test_replay_determinism_with_multislice_gang():
    """The decision trace replays byte-identically through the multi-slice
    + DCN-gang code paths (the extender stays a pure function of its
    request stream)."""
    from tpukube.core.config import load_config as _lc
    from tpukube.trace import replay

    cfg = _lc(env={"TPUKUBE_TRACE_CAPACITY": "8192"})
    with SimCluster(cfg, slices={"slice-a": M44, "slice-b": M44}) as c:
        group = PodGroup("dp", min_member=24, allow_dcn=True)
        for i in range(24):
            c.schedule(c.make_pod(f"d-{i}", tpu=1, group=group))
        for i in range(4):
            c.schedule(c.make_pod(f"s-{i}", tpu=1))
        events = c.extender.trace.events()
        assert events
        divergences = replay(events, config=cfg)
        assert not divergences, divergences[0]

"""A day in the life of a tpukube cluster — every control-plane loop
composed through one (fake) apiserver, stepped deterministically.

This is the "works on a real cluster" capstone: the node agent and the
scheduler NEVER talk to each other directly; everything flows the way it
does in production — annotation file -> syncer -> Node object -> refresh
loop -> names-only webhooks -> Binding subresource -> alloc annotation ->
intent watcher -> GetPreferredAllocation -> Allocate -> divergence report
-> reconcile -> preemption -> Eviction subresource -> health fault ->
re-annotation -> capacity shrink (SURVEY.md §4.1-§4.4 end to end).
"""

import json

import pytest

from tpukube import apiserver as apisrv
from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sched.extender import Extender


def _pod_obj(name, tpu, priority=0, group=None, namespace="default"):
    annotations = {}
    if group is not None:
        annotations.update(codec.pod_group_annotations(group))
    return {
        "metadata": {
            "name": name, "namespace": namespace,
            "uid": f"uid-{name}", "annotations": annotations,
        },
        "spec": {
            "priority": priority,
            "containers": [{
                "name": "main",
                "resources": {
                    "requests": {"qiniu.com/tpu": str(tpu)},
                },
            }],
        },
    }


def _wait_for(predicate, what, timeout=10.0):
    """Bounded wait for a watch-thread effect (the capstone runs the
    intent watcher and lifecycle loop in REAL watch mode — events apply
    on their threads, so the test waits for the effect instead of
    stepping check_once)."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        if predicate():
            return
        _time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def _schedule(ext, api, pod_obj):
    """One kube-scheduler cycle in nodeCacheCapable mode: names-only
    filter -> prioritize -> pick max -> bind (the extender's binder does
    the real Binding against the apiserver)."""
    names = [n["metadata"]["name"] for n in api.node_objects()]
    fres = ext.handle("filter", {"Pod": pod_obj, "NodeNames": names})
    if fres.get("Error"):
        raise RuntimeError(f"filter error: {fres['Error']}")
    if not fres["NodeNames"]:
        raise RuntimeError(f"unschedulable: {fres['FailedNodes']}")
    pres = ext.handle(
        "prioritize", {"Pod": pod_obj, "NodeNames": fres["NodeNames"]}
    )
    scores = {e["Host"]: e["Score"] for e in pres}
    best = max(sorted(scores), key=lambda h: scores[h])
    meta = pod_obj["metadata"]
    bres = ext.handle("bind", {
        "PodName": meta["name"], "PodNamespace": meta["namespace"],
        "PodUID": meta["uid"], "Node": best,
    })
    if bres.get("Error"):
        raise RuntimeError(f"bind error: {bres['Error']}")
    return best


def test_full_cluster_lifecycle(tmp_path):
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer, FakeKubelet
    from tpukube.plugin.server import HealthWatcher

    cfg = load_config(env={
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(16 << 30),
    })
    api = apisrv.FakeApiServer()
    anno_file = tmp_path / "annotation.json"

    with TpuDeviceManager(cfg, host="host-0-0-0") as device, \
            DevicePluginServer(cfg, device) as server, \
            FakeKubelet(str(tmp_path)) as kubelet:
        # ---- node agent boots (SURVEY §4.1) ----------------------------
        server.register_with_kubelet()
        kubelet.wait_for_devices(server.resource_name, 4)

        def write_annotation():
            anno_file.write_text(json.dumps(
                codec.annotate_node(device.node_info(), device.mesh)
            ) + "\n")

        write_annotation()
        health = HealthWatcher(device, server, poll_seconds=999,
                               on_transition=write_annotation)
        health._last = device.health_snapshot()
        syncer = apisrv.NodeAnnotationSyncer(
            api, "host-0-0-0", str(anno_file), poll_seconds=999
        )
        assert syncer.check_once() is True

        # ---- scheduler boots: rebuild (empty) + refresh ----------------
        ext = Extender(cfg)
        ext.binder = apisrv.pod_binder(api)
        server.set_alloc_reporter(apisrv.alloc_divergence_reporter(api))
        refresh = apisrv.NodeTopologyRefreshLoop(ext, api, poll_seconds=999)
        # WATCH mode for both pod-watching loops — the production
        # configuration: intents land within ms of the bind, releases
        # within ms of the deletion. poll_seconds=999 ensures every
        # observed effect below came through the watch stream, never the
        # poll fallback.
        intent_watch = apisrv.AllocIntentWatcher(
            api, "host-0-0-0", server, poll_seconds=999, use_watch=True
        )
        reconcile = apisrv.AllocReconcileLoop(ext, api, poll_seconds=999)
        evictions = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
        lifecycle = apisrv.PodLifecycleReleaseLoop(
            ext, api, poll_seconds=999, use_watch=True, evictions=evictions
        )
        # the daemon's shape: ONE pod stream fanned to both pod loops
        pod_informer = apisrv.PodInformer(
            api, [lifecycle, reconcile], poll_seconds=999
        )
        assert apisrv.rebuild_extender(ext, api) == 0
        assert refresh.check_once() is True  # topology flows api -> cache
        intent_watch.start()
        pod_informer.start()

        # ---- pod lifecycle: schedule -> steer -> allocate (§4.2-§4.3) --
        pod = _pod_obj("train-0", tpu=2)
        api.upsert_pod(pod)
        node = _schedule(ext, api, pod)
        assert node == "host-0-0-0"
        bound = api.get_pod("default", "train-0")
        assert bound["spec"]["nodeName"] == node  # the REAL binding
        planned = codec.decode_alloc(
            bound["metadata"]["annotations"][codec.ANNO_ALLOC]
        ).device_ids

        _wait_for(  # plan reaches the agent through the WATCH stream
            lambda: sorted(
                server.intents.snapshot().get("default/train-0") or []
            ) == sorted(planned),
            "train-0 intent via watch",
        )
        devs = sorted(kubelet.wait_for_devices(server.resource_name, 4))
        steered = kubelet.preferred(server.resource_name, devs, 2)
        assert sorted(steered) == sorted(planned)  # kubelet follows plan
        env = kubelet.allocate(server.resource_name, steered)
        assert env["TPU_KUBE_DEVICE_IDS"].split(",") == sorted(steered)
        assert server.divergences == 0

        # ---- a divergent kubelet choice is reconciled (§4.3 loop) ------
        pod2 = _pod_obj("train-1", tpu=1)
        api.upsert_pod(pod2)
        _schedule(ext, api, pod2)
        planned2 = codec.decode_alloc(
            api.get_pod("default", "train-1")
            ["metadata"]["annotations"][codec.ANNO_ALLOC]
        ).device_ids
        _wait_for(
            lambda: "default/train-1" in server.intents.snapshot(),
            "train-1 intent via watch",
        )
        free = [d for d in devs if d not in steered and d not in planned2]
        kubelet.allocate(server.resource_name, [free[0]])  # ignores plan
        assert server.divergences == 1
        # the reporter thread PATCHes alloc-actual; the informer's WATCH
        # delivers that MODIFIED event to the reconcile handler, which
        # folds reality into the ledger — no poll anywhere
        _wait_for(
            lambda: reconcile.reconciled == 1,  # counts AFTER the ack
            "divergence reconciled via watch",
        )
        assert ext.state.allocation("default/train-1").device_ids == [free[0]]
        fixed = codec.decode_alloc(
            api.get_pod("default", "train-1")
            ["metadata"]["annotations"][codec.ANNO_ALLOC]
        )
        assert fixed.device_ids == [free[0]]

        # ---- preemption: gang evicts via the Eviction subresource ------
        # the first member's bind executes the plan, then FAILS retryably
        # until the victims' pod objects are confirmed gone (the eviction
        # executor's drain + confirm, exactly as the daemon loop runs it)
        ext.evict_precheck = (
            lambda pod_key: api.evict_pod(*pod_key.split("/", 1),
                                          dry_run=True)
        )
        gang = PodGroup("vip", min_member=4)
        victims_before = {p["metadata"]["name"] for p in api.list_pods()}
        import time as _t
        for i in range(4):
            gp = _pod_obj(f"vip-{i}", tpu=1, priority=100, group=gang)
            api.upsert_pod(gp)
            for attempt in range(100):  # kube-scheduler's requeue
                try:
                    _schedule(ext, api, gp)
                    break
                except RuntimeError as e:
                    if "victim" not in str(e):
                        raise
                    # drain the queue; confirmation arrives via the
                    # lifecycle WATCH thread (DELETED events), so give
                    # it a beat before the next cycle
                    evictions.check_once()
                    _t.sleep(0.01)
            else:
                raise AssertionError(f"vip-{i} never bound")
        remaining = {p["metadata"]["name"] for p in api.list_pods()}
        evicted = victims_before - remaining
        assert evicted == {"train-0", "train-1"}  # preempted via the api
        assert evictions.evicted == 2
        res = ext.gang.reservation("default", "vip")
        assert res is not None and res.committed

        # ---- health fault shrinks capacity end to end (§4.4) -----------
        device.inject_fault(0)
        assert health.check_once() is True   # kubelet push + re-annotate
        assert syncer.check_once() is True   # file -> Node object
        assert refresh.check_once() is True  # Node -> extender cache
        pod3 = _pod_obj("late", tpu=1)
        api.upsert_pod(pod3)
        with pytest.raises(RuntimeError, match="unschedulable"):
            _schedule(ext, api, pod3)  # 4 chips: 4 vip + 0 healthy free
        # recovery reopens the node
        device.inject_fault(0, healthy=True)
        assert health.check_once() and syncer.check_once()
        assert refresh.check_once() is True
        # all-or-nothing holds: a released gang member's chip stays
        # reserved for a REPLACEMENT member, never for bystanders. The
        # release is the lifecycle loop observing the DELETED event on
        # its watch stream — no manual release call anywhere in this
        # cluster's day.
        api.delete_pod("default", "vip-3")
        _wait_for(
            lambda: ext.state.allocation("default/vip-3") is None,
            "vip-3 release via watch",
        )
        with pytest.raises(RuntimeError, match="unschedulable"):
            _schedule(ext, api, pod3)
        replacement = _pod_obj("vip-3b", tpu=1, priority=100, group=gang)
        api.upsert_pod(replacement)
        assert _schedule(ext, api, replacement) == "host-0-0-0"
        assert api.get_pod("default", "vip-3b")["spec"]["nodeName"]

        # ---- the job finishes: terminal phases recycle the chips -------
        # completed Job pods LINGER as objects (phase Succeeded); only the
        # lifecycle loop's phase rule returns their chips, the gang
        # dissolves with its last member, and the bystander finally fits
        for name in ("vip-0", "vip-1", "vip-2", "vip-3b"):
            obj = api.get_pod("default", name)
            obj.setdefault("status", {})["phase"] = "Succeeded"
            api.upsert_pod(obj)
        _wait_for(
            lambda: ext.state.utilization() == 0.0,
            "terminal-phase releases via watch",
        )
        assert ext.gang.reservation("default", "vip") is None
        assert _schedule(ext, api, pod3) == "host-0-0-0"

        intent_watch.stop()
        pod_informer.stop()

        # the whole day replays deterministically from the trace
        from tpukube import trace as trace_mod
        assert ext.trace is not None
        assert trace_mod.replay(ext.trace.events(), config=cfg) == []

import pytest

from tpukube.core.config import load_config
from tpukube.core.types import Health
from tpukube.device import DeviceError, TpuDeviceManager
from tpukube.device.tpu import (
    ENV_HBM_LIMIT,
    ENV_KUBE_CHIP_COORDS,
    ENV_KUBE_CORE_IDS,
    ENV_KUBE_MESH_DIMS,
    ENV_MEM_FRACTION,
    ENV_VISIBLE_DEVICES,
)

HBM = 16 << 30


def _mgr(shares=1, host="host-0-0-0"):
    cfg = load_config(env={
        "TPUKUBE_SHARES_PER_CHIP": str(shares),
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    })
    return TpuDeviceManager(cfg, host=host)


def test_whole_chip_mode_advertises_chips():
    with _mgr() as m:
        assert m.resource_name == "qiniu.com/tpu"
        devs = m.device_list()
        assert [d for d, _ in devs] == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
        assert all(h is Health.HEALTHY for _, h in devs)


def test_vtpu_mode_advertises_shares_only():
    with _mgr(shares=2) as m:
        assert m.resource_name == "qiniu.com/vtpu"
        ids = [d for d, _ in m.device_list()]
        assert len(ids) == 8
        assert "tpu-0-frac0of2" in ids and "tpu-3-frac1of2" in ids
        assert all("frac" in d for d in ids)


def test_allocate_env_whole_chips():
    with _mgr() as m:
        env = m.allocate_env(["tpu-2", "tpu-0"])
        assert env[ENV_VISIBLE_DEVICES] == "0,2"
        assert env[ENV_KUBE_MESH_DIMS] == "4,4,1"
        assert env[ENV_HBM_LIMIT] == str(2 * HBM)
        assert env[ENV_KUBE_CHIP_COORDS] == "0,0,0;0,1,0"
        assert ENV_MEM_FRACTION not in env  # no cap in whole-chip mode


def test_allocate_env_fractional_sets_quota():
    with _mgr(shares=2) as m:
        env = m.allocate_env(["tpu-1-frac0of2"])
        assert env[ENV_VISIBLE_DEVICES] == "1"
        assert env[ENV_HBM_LIMIT] == str(HBM // 2)
        assert env[ENV_MEM_FRACTION] == "0.5000"
        # both shares of one chip -> full chip quota
        env = m.allocate_env(["tpu-2-frac0of2", "tpu-2-frac1of2"])
        assert env[ENV_HBM_LIMIT] == str(HBM)
        assert env[ENV_MEM_FRACTION] == "1.0000"
        # uneven shares across chips: XLA applies the fraction per device,
        # so the cap must protect the most-constrained chip (1 share = 0.5)
        env = m.allocate_env(
            ["tpu-2-frac0of2", "tpu-2-frac1of2", "tpu-1-frac0of2"]
        )
        assert env[ENV_HBM_LIMIT] == str(HBM + HBM // 2)
        assert env[ENV_MEM_FRACTION] == "0.5000"


def test_allocate_rejects_mode_mismatch_and_junk():
    with _mgr() as m:
        with pytest.raises(DeviceError, match="vTPU id rejected"):
            m.allocate_env(["tpu-0-frac0of2"])
        with pytest.raises(DeviceError, match="malformed"):
            m.allocate_env(["gpu-0"])
        with pytest.raises(DeviceError, match="duplicate"):
            m.allocate_env(["tpu-0", "tpu-0"])
        with pytest.raises(DeviceError, match="empty"):
            m.allocate_env([])
        with pytest.raises(DeviceError, match="unknown chip"):
            m.allocate_env(["tpu-9"])
    with _mgr(shares=2) as m:
        with pytest.raises(DeviceError, match="whole-chip id rejected"):
            m.allocate_env(["tpu-0"])
        with pytest.raises(DeviceError, match="does not match"):
            m.allocate_env(["tpu-0-frac0of4"])


def test_allocate_rejects_unhealthy():
    with _mgr() as m:
        m.inject_fault(1)
        with pytest.raises(DeviceError, match="unhealthy"):
            m.allocate_env(["tpu-1"])
        m.allocate_env(["tpu-0"])  # healthy chips still allocatable


def test_preferred_allocation_prefers_adjacency():
    # host block is 2x2x1: chips 0,1,2,3 at (0,0),(1,0),(0,1),(1,1).
    with _mgr() as m:
        chosen = m.preferred_allocation(
            ["tpu-0", "tpu-1", "tpu-2", "tpu-3"], [], 2
        )
        # any adjacent pair is acceptable; first pick is deterministic tpu-0
        assert chosen[0] == "tpu-0"
        assert chosen[1] in ("tpu-1", "tpu-2")  # neighbors of chip 0, not diagonal
        chosen = m.preferred_allocation(
            ["tpu-0", "tpu-1", "tpu-2", "tpu-3"], ["tpu-3"], 3
        )
        assert chosen[0] == "tpu-3" and len(set(chosen)) == 3


def test_preferred_allocation_colocates_vtpu_shares():
    with _mgr(shares=2) as m:
        avail = [
            "tpu-0-frac0of2", "tpu-0-frac1of2",
            "tpu-1-frac0of2", "tpu-1-frac1of2",
        ]
        chosen = m.preferred_allocation(avail, [], 2)
        # both shares of one chip beat a cross-chip neighbor pair
        chips = {c.split("-frac")[0] for c in chosen}
        assert len(chips) == 1, chosen


def test_preferred_allocation_skips_unhealthy():
    with _mgr() as m:
        m.inject_fault(1)
        chosen = m.preferred_allocation(
            ["tpu-0", "tpu-1", "tpu-2", "tpu-3"], [], 3
        )
        assert "tpu-1" not in chosen and len(chosen) == 3
        with pytest.raises(DeviceError, match="only 3 healthy"):
            m.preferred_allocation(["tpu-0", "tpu-1", "tpu-2", "tpu-3"], [], 4)
        with pytest.raises(DeviceError, match="must-include id tpu-1 is unhealthy"):
            m.preferred_allocation(["tpu-0", "tpu-1"], ["tpu-1"], 1)


def test_preferred_allocation_errors():
    with _mgr() as m:
        with pytest.raises(DeviceError, match="smaller"):
            m.preferred_allocation(["tpu-0"], ["tpu-0", "tpu-1"], 1)
        with pytest.raises(DeviceError, match="larger"):
            m.preferred_allocation(["tpu-0"], [], 2)
        with pytest.raises(DeviceError, match="not in available"):
            m.preferred_allocation(["tpu-0"], ["tpu-3"], 1)


def test_vtpu_share_gets_dedicated_tensorcore():
    """BASELINE: the vTPU layer "partitions TPU HBM and TensorCores" — with
    2 shares on a 2-core chip, each share owns exactly one core."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,1,1",
        "TPUKUBE_SHARES_PER_CHIP": "2",
        "TPUKUBE_CORES_PER_CHIP": "2",
    })
    with TpuDeviceManager(cfg) as dev:
        env0 = dev.allocate_env(["tpu-0-frac0of2"])
        env1 = dev.allocate_env(["tpu-0-frac1of2"])
        assert env0[ENV_KUBE_CORE_IDS] == "0:0"
        assert env1[ENV_KUBE_CORE_IDS] == "0:1"
        # both shares of one chip in a single pod -> both cores
        env_both = dev.allocate_env(["tpu-1-frac0of2", "tpu-1-frac1of2"])
        assert env_both[ENV_KUBE_CORE_IDS] == "1:0+1"


def test_vtpu_core_env_absent_when_shares_exceed_cores():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "1,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "1,1,1",
        "TPUKUBE_SHARES_PER_CHIP": "4",
        "TPUKUBE_CORES_PER_CHIP": "2",
    })
    with TpuDeviceManager(cfg) as dev:
        env = dev.allocate_env(["tpu-0-frac2of4"])
        assert ENV_KUBE_CORE_IDS not in env
        assert env[ENV_MEM_FRACTION] == "0.2500"

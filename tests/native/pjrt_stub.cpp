/* Stub PJRT plugin for testing libtpuinfo's real-backend enumeration
 * (tpuinfo.cpp enumerate_pjrt) without TPU hardware.
 *
 * Exposes GetPjrtApi serving a configurable set of fake devices; pointing
 * the real backend's `libtpu=<this .so>` at it executes the entire PJRT
 * C-API enumeration path in CI. Behavior is driven by env vars READ AT
 * PJRT_Client_Create / GetPjrtApi TIME (not dlopen time), so one loaded
 * .so can play every scenario across tests in one process:
 *
 *   PJRT_STUB_DEVICES      total addressable devices (default 8)
 *   PJRT_STUB_CORES        devices (cores) per chip coord (default 2)
 *   PJRT_STUB_GRID_X       chip-grid x extent for coords minting (default 2)
 *   PJRT_STUB_HBM          bytes_limit per device (default 16 GiB)
 *   PJRT_STUB_KIND         device kind string (default "stubtpu")
 *   PJRT_STUB_REMOTE       extra NON-addressable devices appended (default 0)
 *   PJRT_STUB_NO_COORDS    omit the "coords" attribute entirely
 *   PJRT_STUB_PARTIAL_COORDS  only even-id devices get a coords attribute
 *   PJRT_STUB_WRAP         "x,y,z" torus wrap flags served as the "wrap"
 *                          int64[3] attribute
 *   PJRT_STUB_NO_MEMSTATS  null out PJRT_Device_MemoryStats (old plugin)
 *   PJRT_STUB_OLD_STRUCT   report a struct_size predating Client_Create
 *   PJRT_STUB_BAD_MAJOR    report an incompatible PJRT major version
 *   PJRT_STUB_FAIL_CLIENT  PJRT_Client_Create returns an error (chip busy)
 *   PJRT_STUB_FAIL_FILE    path: Client_Create fails WHILE this file
 *                          exists — lets another process flip a running
 *                          daemon's canary (env can't be changed from
 *                          outside)
 */
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

struct StubError {
  std::string msg;
};

/* PJRT_Device and PJRT_DeviceDescription are opaque to callers; both are
 * cast to/from this. */
struct StubDevice {
  int id = 0;
  bool addressable = true;
  int64_t coords[3] = {0, 0, 0};
  int64_t wrap[3] = {0, 0, 0};
  std::string kind;
  int64_t hbm = 0;
  std::vector<PJRT_NamedValue> attrs;
};

std::vector<StubDevice> g_devices;
std::vector<PJRT_Device*> g_device_ptrs;
int g_client_token;  /* PJRT_Client* points here */
PJRT_Api g_api;

int64_t env_i64(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  return (v && *v) ? std::strtoll(v, nullptr, 10) : dflt;
}

bool env_flag(const char* name) {
  const char* v = std::getenv(name);
  return v != nullptr && *v != '\0' && std::strcmp(v, "0") != 0;
}

PJRT_Error* make_error(const std::string& m) {
  return reinterpret_cast<PJRT_Error*>(new StubError{m});
}

void build_devices() {
  int n = (int)env_i64("PJRT_STUB_DEVICES", 8);
  int cores = (int)env_i64("PJRT_STUB_CORES", 2);
  if (cores <= 0) cores = 1;
  int grid_x = (int)env_i64("PJRT_STUB_GRID_X", 2);
  if (grid_x <= 0) grid_x = 1;
  int remote = (int)env_i64("PJRT_STUB_REMOTE", 0);
  int64_t hbm = env_i64("PJRT_STUB_HBM", 16LL << 30);
  const char* kind = std::getenv("PJRT_STUB_KIND");
  bool no_coords = env_flag("PJRT_STUB_NO_COORDS");
  bool partial_coords = env_flag("PJRT_STUB_PARTIAL_COORDS");
  int64_t wrap[3] = {0, 0, 0};
  bool have_wrap = false;
  if (const char* w = std::getenv("PJRT_STUB_WRAP")) {
    long wx = 0, wy = 0, wz = 0;
    if (std::sscanf(w, "%ld,%ld,%ld", &wx, &wy, &wz) == 3) {
      wrap[0] = wx; wrap[1] = wy; wrap[2] = wz;
      have_wrap = true;
    }
  }

  g_devices.clear();
  g_device_ptrs.clear();
  g_devices.resize(n + remote);
  for (int i = 0; i < n + remote; ++i) {
    StubDevice& d = g_devices[i];
    d.id = i;
    d.addressable = i < n;
    d.kind = (kind && *kind) ? kind : "stubtpu";
    d.hbm = hbm;
    int chip = i / cores;
    d.coords[0] = chip % grid_x;
    d.coords[1] = chip / grid_x;
    d.coords[2] = 0;
    d.wrap[0] = wrap[0];
    d.wrap[1] = wrap[1];
    d.wrap[2] = wrap[2];
  }
  /* attrs reference per-device storage: build only after g_devices is at
   * its final size (no reallocation moves the pointed-to coords) */
  for (auto& d : g_devices) {
    d.attrs.clear();
    PJRT_NamedValue pi;
    std::memset(&pi, 0, sizeof pi);
    pi.struct_size = PJRT_NamedValue_STRUCT_SIZE;
    pi.name = "process_index";
    pi.name_size = std::strlen(pi.name);
    pi.type = PJRT_NamedValue_kInt64;
    pi.int64_value = 0;
    pi.value_size = 1;
    d.attrs.push_back(pi);  /* a scalar attr enumerators must skip over */
    if (!no_coords && !(partial_coords && d.id % 2 == 1)) {
      PJRT_NamedValue nv;
      std::memset(&nv, 0, sizeof nv);
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = "coords";
      nv.name_size = std::strlen(nv.name);
      nv.type = PJRT_NamedValue_kInt64List;
      nv.int64_array_value = d.coords;
      nv.value_size = 3;
      d.attrs.push_back(nv);
    }
    if (have_wrap) {
      PJRT_NamedValue nv;
      std::memset(&nv, 0, sizeof nv);
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = "wrap";
      nv.name_size = std::strlen(nv.name);
      nv.type = PJRT_NamedValue_kInt64List;
      nv.int64_array_value = d.wrap;
      nv.value_size = 3;
      d.attrs.push_back(nv);
    }
    g_device_ptrs.push_back(reinterpret_cast<PJRT_Device*>(&d));
  }
}

void stub_error_destroy(PJRT_Error_Destroy_Args* a) {
  delete reinterpret_cast<StubError*>(a->error);
}

void stub_error_message(PJRT_Error_Message_Args* a) {
  const auto* e = reinterpret_cast<const StubError*>(a->error);
  a->message = e->msg.c_str();
  a->message_size = e->msg.size();
}

PJRT_Error* stub_plugin_initialize(PJRT_Plugin_Initialize_Args*) {
  return nullptr;
}

PJRT_Error* stub_client_create(PJRT_Client_Create_Args* a) {
  if (env_flag("PJRT_STUB_FAIL_CLIENT"))
    return make_error("stub: device busy (owned by another process)");
  if (const char* f = std::getenv("PJRT_STUB_FAIL_FILE")) {
    FILE* fp = std::fopen(f, "r");
    if (fp != nullptr) {
      std::fclose(fp);
      return make_error("stub: chip fault (fail-file present)");
    }
  }
  build_devices();
  a->client = reinterpret_cast<PJRT_Client*>(&g_client_token);
  return nullptr;
}

PJRT_Error* stub_client_destroy(PJRT_Client_Destroy_Args*) { return nullptr; }

PJRT_Error* stub_client_devices(PJRT_Client_Devices_Args* a) {
  a->devices = g_device_ptrs.data();
  a->num_devices = g_device_ptrs.size();
  return nullptr;
}

PJRT_Error* stub_device_get_description(PJRT_Device_GetDescription_Args* a) {
  a->device_description =
      reinterpret_cast<PJRT_DeviceDescription*>(a->device);
  return nullptr;
}

PJRT_Error* stub_device_is_addressable(PJRT_Device_IsAddressable_Args* a) {
  a->is_addressable = reinterpret_cast<StubDevice*>(a->device)->addressable;
  return nullptr;
}

PJRT_Error* stub_desc_id(PJRT_DeviceDescription_Id_Args* a) {
  a->id = reinterpret_cast<StubDevice*>(a->device_description)->id;
  return nullptr;
}

PJRT_Error* stub_desc_kind(PJRT_DeviceDescription_Kind_Args* a) {
  const auto* d = reinterpret_cast<StubDevice*>(a->device_description);
  a->device_kind = d->kind.c_str();
  a->device_kind_size = d->kind.size();
  return nullptr;
}

PJRT_Error* stub_desc_attributes(PJRT_DeviceDescription_Attributes_Args* a) {
  const auto* d = reinterpret_cast<StubDevice*>(a->device_description);
  a->attributes = d->attrs.data();
  a->num_attributes = d->attrs.size();
  return nullptr;
}

PJRT_Error* stub_device_memory_stats(PJRT_Device_MemoryStats_Args* a) {
  const auto* d = reinterpret_cast<StubDevice*>(a->device);
  a->bytes_in_use = 0;
  a->bytes_limit = d->hbm;
  a->bytes_limit_is_set = true;
  return nullptr;
}

}  // namespace

extern "C" const PJRT_Api* GetPjrtApi() {
  std::memset(&g_api, 0, sizeof g_api);
  g_api.struct_size = PJRT_Api_STRUCT_SIZE;
  g_api.pjrt_api_version.struct_size = PJRT_Api_Version_STRUCT_SIZE;
  g_api.pjrt_api_version.major_version =
      env_flag("PJRT_STUB_BAD_MAJOR") ? PJRT_API_MAJOR + 1 : PJRT_API_MAJOR;
  g_api.pjrt_api_version.minor_version = PJRT_API_MINOR;
  g_api.PJRT_Error_Destroy = stub_error_destroy;
  g_api.PJRT_Error_Message = stub_error_message;
  g_api.PJRT_Plugin_Initialize = stub_plugin_initialize;
  g_api.PJRT_Client_Create = stub_client_create;
  g_api.PJRT_Client_Destroy = stub_client_destroy;
  g_api.PJRT_Client_Devices = stub_client_devices;
  g_api.PJRT_Device_GetDescription = stub_device_get_description;
  g_api.PJRT_Device_IsAddressable = stub_device_is_addressable;
  g_api.PJRT_DeviceDescription_Id = stub_desc_id;
  g_api.PJRT_DeviceDescription_Kind = stub_desc_kind;
  g_api.PJRT_DeviceDescription_Attributes = stub_desc_attributes;
  if (!env_flag("PJRT_STUB_NO_MEMSTATS"))
    g_api.PJRT_Device_MemoryStats = stub_device_memory_stats;
  if (env_flag("PJRT_STUB_OLD_STRUCT")) {
    /* a plugin built against an ancient header: its PJRT_Api ends before
     * the entry points the enumerator requires */
    g_api.struct_size = offsetof(PJRT_Api, PJRT_Client_Create);
  }
  return &g_api;
}

"""Durable control-plane journal + checkpointed crash recovery
(ISSUE 11): WAL/checkpoint durability edges, the crash-at-every-record-
boundary property (recovered state equals a from-scratch rebuild), the
CrashSchedule seams, lazy node materialization, and the journal-off
parity contract (placements + exposition byte-identical).
"""

from __future__ import annotations

import json
import os
import shutil
import time

import pytest

from tpukube.chaos import crash as crash_mod
from tpukube.chaos import ledger_divergence
from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sched import journal as journal_mod
from tpukube.sched.extender import Extender
from tpukube.sched.journal import (
    JournalError,
    StateJournal,
    load_checkpoint,
    load_wal,
    recover_extender,
)
from tpukube.sim.harness import SimCluster


def _cfg(tmp_path, **extra):
    env = {
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_JOURNAL_ENABLED": "1",
        "TPUKUBE_JOURNAL_PATH": str(tmp_path / "wal.jsonl"),
    }
    env.update(extra)
    return load_config(env=env)


def _fingerprint(ext) -> dict:
    """The recovered-state equality the property test asserts on:
    allocations, gang reservations, and the per-slice scheduling sets
    every placement decision derives from."""
    ext.state.warm_pending(limit=1 << 20)  # materialize everything
    snap = ext.snapshots._build(ext.snapshots.epoch_key())
    return {
        "allocs": sorted(
            (a.pod_key, a.node_name, tuple(sorted(a.device_ids)))
            for a in ext.state.allocations()
        ),
        "gangs": ext.gang_snapshot(),
        "slices": {
            sid: {
                "occupied": sorted(map(tuple, ss.occupied)),
                "reserved": sorted(map(tuple, ss.reserved)),
                "unhealthy": sorted(map(tuple, ss.unhealthy)),
                "terminating": sorted(map(tuple, ss.terminating)),
                "used": ss.used_shares,
                "total": ss.total_shares,
            }
            for sid, ss in snap.slices.items()
        },
        "nodes": sorted(ext.state.node_names()),
    }


def _drive_workload(c: SimCluster) -> None:
    """A mixed mutation sequence covering the journaled seams: gang
    assembly + commit, plain binds, completions, deletions, and a
    health-only re-annotation."""
    group = PodGroup("jg", min_member=4)
    for i in range(4):
        c.schedule(c.make_pod(f"jg-{i}", tpu=1, priority=10, group=group))
    for i in range(5):
        c.schedule(c.make_pod(f"b-{i}", tpu=1))
    c.complete_pod("b-0")
    c.delete_pod("b-1")
    c.schedule(c.make_pod("b-5", tpu=1))
    c.inject_fault("host-1-1-0", 0)
    c._sync_nodes.__self__._synced_objs = []  # force a re-send
    c._sync_nodes()
    c.schedule(c.make_pod("b-6", tpu=1))


# -- WAL + checkpoint unit edges ---------------------------------------------

def test_wal_roundtrip_and_seq_continuity(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    j = StateJournal(path)
    j.note("commit", {"a": "x"})
    j.note("release", {"p": "default/p0"})
    j.close()
    records, info = load_wal(path)
    assert [r["k"] for r in records] == ["commit", "release"]
    assert [r["s"] for r in records] == [1, 2]
    assert info == {"torn": 0, "bad_crc": 0}
    # a fresh incarnation continues numbering off the file tail
    j2 = StateJournal(path)
    j2.note("commit", {"a": "y"})
    j2.close()
    records, _ = load_wal(path)
    assert [r["s"] for r in records] == [1, 2, 3]


def test_wal_torn_tail_truncates(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    j = StateJournal(path)
    for i in range(4):
        j.note("release", {"p": f"default/p{i}"})
    j.close()
    assert crash_mod.tear_wal_tail(path)
    records, info = load_wal(path)
    assert len(records) == 3 and info["torn"] == 1


def test_wal_corrupt_tail_truncates(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    j = StateJournal(path)
    for i in range(4):
        j.note("release", {"p": f"default/p{i}"})
    j.close()
    assert crash_mod.corrupt_wal_tail(path)
    records, info = load_wal(path)
    assert len(records) == 3 and info["bad_crc"] == 1


def test_empty_and_missing_wal(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    assert load_wal(path) == ([], {"torn": 0, "bad_crc": 0})
    open(path, "w").close()
    assert load_wal(path) == ([], {"torn": 0, "bad_crc": 0})
    assert load_checkpoint(path + ".ckpt") is None


def test_checkpoint_roundtrip_and_torn_body_refused(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p0", tpu=1))
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        ckpt_path = c.extender.journal.ckpt_path
        loaded = load_checkpoint(ckpt_path)
        assert loaded is not None
        head, fd, data_start = loaded
        os.close(fd)
        assert head["wal_seq"] >= 1
        assert set(head["node_index"]) == set(c.extender.state.node_names())
        # body torn off behind an intact head line: the whole
        # checkpoint must be refused (its node lines are gone)
        assert crash_mod.tear_checkpoint(ckpt_path)
        assert load_checkpoint(ckpt_path) is None


def test_rotation_then_checkpoint_keeps_wal_appendable(tmp_path):
    """Regression: after a size-cap rotation the live handle must stay
    append-safe across a checkpoint's truncate-to-zero — a stale write
    position would leave a NUL hole that makes the loader discard
    every post-checkpoint record at the next recovery."""
    cfg = _cfg(tmp_path, TPUKUBE_JOURNAL_MAX_BYTES="600")
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"r-{i}", tpu=1))
        time.sleep(0.2)
        assert c.extender.journal.stats()["rotations"] >= 1
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        for i in range(3):
            c.schedule(c.make_pod(f"post-{i}", tpu=1))
        time.sleep(0.2)
        records, info = load_wal(cfg.journal_path)
        assert info == {"torn": 0, "bad_crc": 0}
        assert len(records) >= 3, "post-checkpoint records must load"
        want = _fingerprint(c.extender)
        c.crash_extender()
        c.restart_extender()
        assert c.last_recovery["mode"] == "warm"
        assert c.last_recovery["replayed"] >= 3
        assert _fingerprint(c.extender) == want


def test_seq_continuity_after_checkpoint_truncation(tmp_path):
    """Regression: a landed checkpoint truncates the WAL; a FRESH
    journal on that path must continue numbering from the head line's
    wal_seq, never reuse seqs the checkpoint already covers."""
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        for i in range(3):
            c.schedule(c.make_pod(f"p{i}", tpu=1))
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        seq = c.extender.journal.seq()
        assert seq > 0
    j = StateJournal(cfg.journal_path)
    try:
        assert j.seq() >= seq, (j.seq(), seq)
    finally:
        j.close()


def test_wal_truncated_after_checkpoint_lands(tmp_path):
    """A landed checkpoint covers every record on disk, so the drain
    truncates the log — the O(Δ) restart contract's other half."""
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        for i in range(3):
            c.schedule(c.make_pod(f"p{i}", tpu=1))
        assert os.path.getsize(cfg.journal_path) > 0
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        assert os.path.getsize(cfg.journal_path) == 0


# -- recovery ----------------------------------------------------------------

def test_recovery_without_checkpoint_replays_whole_wal(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        _drive_workload(c)
        want = _fingerprint(c.extender)
        c.crash_extender()
        c.restart_extender()
        assert c.last_recovery["mode"] == "warm"
        assert c.last_recovery["checkpoint"] is False
        assert c.last_recovery["replayed"] > 0
        assert _fingerprint(c.extender) == want
        assert ledger_divergence(c) == []


def test_recovery_from_checkpoint_plus_tail(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        group = PodGroup("jg", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"jg-{i}", tpu=1, priority=10,
                                  group=group))
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        for i in range(3):
            c.schedule(c.make_pod(f"b-{i}", tpu=1))  # the stale tail
        want = _fingerprint(c.extender)
        c.crash_extender()
        c.restart_extender()
        assert c.last_recovery["mode"] == "warm"
        assert c.last_recovery["checkpoint"] is True
        assert c.last_recovery["replayed"] >= 3
        assert _fingerprint(c.extender) == want


def test_recovery_reconciles_lost_tail_records(tmp_path):
    """before-append crash: mutations applied (and visible on the
    apiserver) whose WAL records never hit disk — the reconcile must
    supply the missing truth."""
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"b-{i}", tpu=1))
        time.sleep(0.2)  # let the drain land every record
        want = _fingerprint(c.extender)
        c.crash_extender()
        assert crash_mod.drop_wal_records(cfg.journal_path, drop=3) == 3
        c.restart_extender()
        assert c.last_recovery["divergences"] > 0
        assert _fingerprint(c.extender) == want
        assert ledger_divergence(c) == []


def test_recovery_falls_back_on_wal_gap(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"b-{i}", tpu=1))
        time.sleep(0.2)
        want = _fingerprint(c.extender)
        c.crash_extender()
        # surgically remove a MIDDLE record: the chain has a hole no
        # truncation explains — recovery must refuse and the harness
        # falls back to the legacy full rebuild
        lines = open(cfg.journal_path, "rb").read().splitlines(True)
        with open(cfg.journal_path, "wb") as f:
            f.writelines(lines[:2] + lines[3:])
        c.restart_extender()
        assert c.last_recovery["mode"] == "cold-fallback"
        assert _fingerprint(c.extender) == want
        assert ledger_divergence(c) == []


def test_stale_checkpoint_with_store_drift(tmp_path):
    """The checkpoint + WAL lag the apiserver (records lost AND pods
    moved on): apiserver truth wins through the reconcile."""
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        for i in range(4):
            c.schedule(c.make_pod(f"b-{i}", tpu=1))
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        # post-checkpoint history the crash will erase from the WAL:
        c.schedule(c.make_pod("late-0", tpu=1))
        c.complete_pod("b-0")
        time.sleep(0.2)
        want = _fingerprint(c.extender)
        c.crash_extender()
        crash_mod.drop_wal_records(cfg.journal_path, drop=10_000)
        c.restart_extender()
        assert c.last_recovery["checkpoint"] is True
        assert c.last_recovery["divergences"] > 0
        assert _fingerprint(c.extender) == want


# -- the property: crash at EVERY record boundary ----------------------------

@pytest.mark.parametrize("with_checkpoint", [False, True])
def test_crash_at_every_record_boundary_equals_rebuild(
    tmp_path, with_checkpoint
):
    """ISSUE 11 acceptance property: for a crash at ANY record
    boundary — the WAL truncated to its first k records — recovery
    (checkpoint + prefix replay + apiserver reconcile) must equal the
    from-scratch rebuild against the same apiserver. The prefix is
    arbitrarily stale history; the reconcile owns convergence."""
    from tpukube.apiserver import rebuild_extender

    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        group = PodGroup("jg", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"jg-{i}", tpu=1, priority=10,
                                  group=group))
        if with_checkpoint:
            c.extender.journal.write_checkpoint_sync(
                c.extender.checkpoint_doc()
            )
        for i in range(4):
            c.schedule(c.make_pod(f"b-{i}", tpu=1))
        c.complete_pod("b-0")
        c.delete_pod("b-1")
        c.schedule(c.make_pod("b-4", tpu=1))
        c.crash_extender()
        store_api = c._store_api

        # the from-scratch oracle against the final store
        from dataclasses import replace as dc_replace

        cold_cfg = dc_replace(cfg, journal_enabled=False,
                              journal_path="")
        oracle = Extender(cold_cfg)
        rebuild_extender(oracle, store_api)
        want = _fingerprint(oracle)

        records, _ = load_wal(cfg.journal_path)
        src = str(tmp_path)
        for k in range(len(records) + 1):
            case = tmp_path / f"case-{k}"
            case.mkdir()
            for fn in os.listdir(src):
                if fn.startswith("wal.jsonl"):
                    shutil.copy(os.path.join(src, fn), case / fn)
            wal_k = str(case / "wal.jsonl")
            crash_mod.drop_wal_records(wal_k, drop=len(records) - k)
            k_cfg = dc_replace(cfg, journal_path=wal_k)
            ext = Extender(k_cfg)
            try:
                recover_extender(ext, store_api)
                got = _fingerprint(ext)
            finally:
                ext.journal.crash()
                ext.state.retire()
            assert got == want, f"boundary {k}: recovered state diverged"


# -- CrashSchedule -----------------------------------------------------------

def test_crash_schedule_deterministic_and_covering():
    a = crash_mod.CrashSchedule(7)
    b = crash_mod.CrashSchedule(7)
    seams_a = [a.next_seam() for _ in range(10)]
    seams_b = [b.next_seam() for _ in range(10)]
    assert seams_a == seams_b
    # the first len(CRASH_SEAMS) draws cover every outcome
    n = len(crash_mod.CRASH_SEAMS)
    assert set(seams_a[:n]) == set(crash_mod.CRASH_SEAMS)


# -- lazy materialization ----------------------------------------------------

def test_lazy_nodes_materialize_on_demand(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        c.schedule(c.make_pod("p0", tpu=1))
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        c.crash_extender()
        c.restart_extender()
        state = c.extender.state
        lazy_before = len(state._lazy_index)
        assert lazy_before > 0, "restore should leave nodes lazy"
        # unchanged-payload compares must not materialize
        view0 = state.node("host-0-0-0")  # materializes exactly one
        assert view0 is not None
        assert len(state._lazy_index) >= lazy_before - 1
        # the audit sentinel materializes the fleet and must agree
        c.extender.snapshots.audit_now()
        # serving still works end to end
        c.schedule(c.make_pod("p1", tpu=1))
        assert ledger_divergence(c) == []


def test_recovery_preserves_node_names_and_payload_compare(tmp_path):
    cfg = _cfg(tmp_path)
    with SimCluster(cfg) as c:
        names_before = None
        c.schedule(c.make_pod("p0", tpu=1))
        names_before = c.extender.state.node_names()
        c.extender.journal.write_checkpoint_sync(
            c.extender.checkpoint_doc()
        )
        c.crash_extender()
        c.restart_extender()
        state = c.extender.state
        assert state.node_names() == names_before
        for obj in c.node_objects():
            name = obj["metadata"]["name"]
            payload = obj["metadata"]["annotations"][
                codec.ANNO_NODE_TOPOLOGY]
            assert state.payload_matches(name, payload)
        assert not state.payload_matches("host-0-0-0", "junk")


# -- satellites: node_names cache ------------------------------------------

def test_node_names_cached_tuple_invalidated_on_node_set_change():
    from tpukube.core import codec as codec_mod
    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import ChipInfo, NodeInfo
    from tpukube.sched.state import ClusterState

    cfg = load_config(env={})
    mesh = MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))
    state = ClusterState()

    def add(host):
        chips = [
            ChipInfo(chip_id=f"{host}-c{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        state.upsert_node(host, codec_mod.annotate_node(
            NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id),
            mesh))

    add("host-0-0-0")
    first = state.node_names()
    assert isinstance(first, tuple)
    # stable identity while the node SET stands still (the satellite:
    # per-cycle callers must not pay a fresh sort-and-copy)
    assert state.node_names() is first
    # a re-annotation of an EXISTING node keeps the cache...
    add("host-0-0-0")
    assert state.node_names() is first


# -- parity: journal off is byte-identical -----------------------------------

def _run_placements(cfg) -> list:
    with SimCluster(cfg) as c:
        group = PodGroup("pg", min_member=4)
        out = []
        for i in range(4):
            node, alloc = c.schedule(
                c.make_pod(f"g-{i}", tpu=1, priority=10, group=group))
            out.append((node, tuple(alloc.device_ids)))
        for i in range(4):
            node, alloc = c.schedule(c.make_pod(f"b-{i}", tpu=1))
            out.append((node, tuple(alloc.device_ids)))
        c.complete_pod("b-0")
        node, alloc = c.schedule(c.make_pod("b-9", tpu=1))
        out.append((node, tuple(alloc.device_ids)))
        return out


def test_journal_parity_placements_identical(tmp_path):
    base = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    assert _run_placements(base) == _run_placements(_cfg(tmp_path))


def test_journal_off_exposition_byte_identical(tmp_path):
    """With the journal off nothing renders; with it on, only the
    tpukube_journal_*/checkpoint/recovery series join."""
    from tpukube.metrics import render_extender_metrics

    base = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    off = render_extender_metrics(Extender(base))
    assert "tpukube_journal" not in off
    assert "tpukube_checkpoint" not in off
    assert "tpukube_recovery" not in off
    ext_on = Extender(_cfg(tmp_path))
    on = render_extender_metrics(ext_on)
    ext_on.journal.close()

    def names(text):
        return {ln.split("{")[0].split(" ")[0]
                for ln in text.splitlines()
                if ln and not ln.startswith("#")}

    extra = names(on) - names(off)
    assert extra == {
        "tpukube_journal_appends_total",
        "tpukube_journal_bytes_total",
        "tpukube_checkpoint_seconds",
        "tpukube_checkpoint_seconds_count",
        "tpukube_checkpoint_seconds_sum",
        "tpukube_recovery_seconds",
        "tpukube_recovery_seconds_count",
        "tpukube_recovery_seconds_sum",
        "tpukube_recovery_replayed_deltas_total",
    }, extra
    assert names(off) - names(on) == set()


def test_statusz_journal_section(tmp_path):
    from tpukube.obs.statusz import extender_statusz

    base = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    assert extender_statusz(Extender(base))["journal"] == {
        "enabled": False}
    ext = Extender(_cfg(tmp_path))
    doc = extender_statusz(ext)["journal"]
    ext.journal.close()
    assert doc["enabled"] is True
    assert doc["path"].endswith("wal.jsonl")


def test_config_validation(tmp_path):
    with pytest.raises(ValueError, match="journal_path"):
        load_config(env={"TPUKUBE_JOURNAL_ENABLED": "1"})
    with pytest.raises(ValueError, match="journal_enabled"):
        load_config(env={"TPUKUBE_JOURNAL_PATH": "/tmp/x"})
    with pytest.raises(ValueError, match="journal_fsync"):
        _cfg(tmp_path, TPUKUBE_JOURNAL_FSYNC="sometimes")
    with pytest.raises(ValueError, match="checkpoint_interval"):
        _cfg(tmp_path, TPUKUBE_CHECKPOINT_INTERVAL_SECONDS="0")
    cfg = _cfg(tmp_path, TPUKUBE_JOURNAL_FSYNC="always")
    assert cfg.journal_fsync == "always"


def test_scenario13_smoke(tmp_path, monkeypatch):
    """Tier-1 smoke of the crash storm at 2 cycles (check.sh runs the
    full 8); every invariant (committed gang survives, zero
    divergence, zero leaks, audits clean) is asserted inside."""
    from tpukube.sim import scenarios

    monkeypatch.setenv("TPUKUBE_CRASH_CYCLES", "2")
    monkeypatch.setenv("TPUKUBE_CHAOS_SEED", "1337")
    monkeypatch.setenv("TPUKUBE_SNAPSHOT_AUDIT_RATE", "1.0")
    r = scenarios.run(13)
    assert r["crash_cycles"] == 2
    assert r["leaked_reservations"] == 0
    assert r["ledger_divergence"] == 0
    assert r["snapshot_audit"]["divergences"] == 0

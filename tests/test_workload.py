"""Workload layer: Llama forward/loss, env→mesh bridge, sharded train step,
and the driver graft entry points — all on the virtual 8-device CPU mesh
(conftest.py sets xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukube.workload.llama import LlamaConfig, forward, init_params, loss_fn
from tpukube.workload.meshenv import (
    PodTpuEnv,
    box_shape,
    build_mesh,
    mesh_axes_from_box,
)
from tpukube.workload.train import init_sharded, make_train_step

TINY = LlamaConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                   d_ff=64, max_seq=16)


def test_forward_shapes_and_dtype():
    params = init_params(jax.random.PRNGKey(0), TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, TINY.vocab)
    logits = jax.jit(lambda p, t: forward(p, t, TINY))(params, tokens)
    assert logits.shape == (3, 8, TINY.vocab)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_causality():
    # changing a future token must not change past logits
    params = init_params(jax.random.PRNGKey(0), TINY)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, TINY.vocab)
    t2 = t1.at[0, 6].set((t1[0, 6] + 1) % TINY.vocab)
    l1 = forward(params, t1, TINY)
    l2 = forward(params, t2, TINY)
    np.testing.assert_allclose(l1[0, :6], l2[0, :6], atol=1e-5)
    assert not np.allclose(l1[0, 6:], l2[0, 6:])


def test_loss_decreases_under_training():
    mesh = build_mesh(jax.devices(), 4, 2)
    with mesh:
        params = init_sharded(jax.random.PRNGKey(0), TINY, mesh)
        step, opt_init = make_train_step(TINY, mesh)
        opt_state = opt_init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0,
                                    TINY.vocab)
        losses = []
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_tp_matches_single_device():
    # the sharded step and a pure single-device step compute the same loss
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, TINY.vocab)
    ref = float(loss_fn(init_params(jax.random.PRNGKey(0), TINY), tokens,
                        TINY))
    mesh = build_mesh(jax.devices(), 2, 4)
    with mesh:
        params = init_sharded(jax.random.PRNGKey(0), TINY, mesh)
        step, opt_init = make_train_step(TINY, mesh)
        _, _, loss = step(params, opt_init(params), tokens)
    assert float(loss) == pytest.approx(ref, rel=2e-2), (float(loss), ref)


def test_mesh_env_bridge():
    env = {
        "TPU_VISIBLE_DEVICES": "0,1,2,3",
        "TPU_KUBE_DEVICE_IDS": "tpu-0,tpu-1,tpu-2,tpu-3",
        "TPU_KUBE_CHIP_COORDS": "0,0,0;1,0,0;0,1,0;1,1,0",
        "TPU_KUBE_MESH_DIMS": "4,4,1",
        "TPU_KUBE_HOST": "host-0-0-0",
        "TPU_HBM_LIMIT_BYTES": "1000",
    }
    pe = PodTpuEnv.from_env(env)
    assert pe.visible_chips == (0, 1, 2, 3)
    assert box_shape(pe.coords) == (2, 2, 1)
    dp, tp = mesh_axes_from_box(box_shape(pe.coords))
    assert dp * tp == 4 and tp == 2


def test_box_shape_rejects_non_contiguous():
    with pytest.raises(ValueError):
        box_shape([(0, 0, 0), (2, 0, 0)])
    with pytest.raises(ValueError):
        box_shape([(0, 0, 0), (1, 1, 0)])  # L-shape, not a full box


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("n", [4, 8])
def test_graft_dryrun_multichip(n):
    import __graft_entry__ as g

    g.dryrun_multichip(n)

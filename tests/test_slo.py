"""SLO layer (ISSUE 2 tentpole): metrics parser, burn-rate math,
exposition lint, and the prometheus-rules.yaml <-> registry contract."""

import os

import yaml

from tpukube.core.config import load_config
from tpukube.obs.registry import DEFAULT_BUCKETS
from tpukube.obs.slo import (
    DEFAULT_SLOS,
    burn_rate,
    evaluate,
    histogram_totals,
    parse_metrics,
    referenced_metric_names,
    validate_exposition,
)

DEPLOY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "deploy"
)

HIST = """\
# TYPE lat_seconds_bucket counter
lat_seconds_bucket{le="0.25"} 90
lat_seconds_bucket{le="2.5"} 99
lat_seconds_bucket{le="+Inf"} 100
"""


# -- parser / math -----------------------------------------------------------

def test_parse_metrics_labels_and_escapes():
    samples = parse_metrics(
        'm{source="table (err \\"quoted\\"\\nline\\\\x)"} 1\n'
        "plain 2.5\n"
    )
    assert samples[0].label("source") == 'table (err "quoted"\nline\\x)'
    assert samples[1].name == "plain" and samples[1].value == 2.5
    try:
        parse_metrics("not a metric line !!!\n")
        assert False, "junk must raise"
    except ValueError:
        pass


def test_histogram_totals_and_burn_rate():
    samples = parse_metrics(HIST)
    good, total = histogram_totals(samples, "lat_seconds", "2.5")
    assert (good, total) == (99.0, 100.0)
    # 1% errors on a 1% budget = burn 1.0
    assert burn_rate(good, total, objective=0.99) == 1.0
    # 10% errors on a 1% budget = burn 10
    good, total = histogram_totals(samples, "lat_seconds", "0.25")
    assert burn_rate(good, total, objective=0.99) == 10.0
    # no traffic is not a burning SLO
    assert burn_rate(0, 0, objective=0.99) is None


def test_evaluate_single_snapshot_and_window_delta():
    text = HIST.replace("lat_seconds", "gang_schedule_latency_seconds")
    result = evaluate(text)
    gang = result["gang-schedule-latency"]
    assert gang["total"] == 100.0
    assert gang["error_ratio"] == 0.01
    assert gang["burn_rate"] == 1.0
    assert gang["window"] == "lifetime"
    assert gang["alerts"] == []  # burn 1.0 pages nobody

    # a second snapshot where every NEW observation missed the bucket:
    # windowed burn = 100% errors / 1% budget = 100 -> page + ticket
    later = text.replace('le="2.5"} 99', 'le="2.5"} 99').replace(
        'le="+Inf"} 100', 'le="+Inf"} 110'
    )
    result = evaluate(later, prev_text=text, window_seconds=60)
    gang = result["gang-schedule-latency"]
    assert gang["window"] == "60s"
    assert gang["total"] == 10.0 and gang["good"] == 0.0
    assert gang["burn_rate"] == 100.0
    assert gang["alerts"] == ["page", "ticket"]


def test_slo_thresholds_are_real_bucket_boundaries():
    """A threshold_le that is not a rendered bucket boundary would make
    histogram_totals silently count zero good events."""
    boundaries = {f"{b:g}" for b in DEFAULT_BUCKETS}
    for slo in DEFAULT_SLOS:
        assert slo.threshold_le in boundaries, slo.name


# -- exposition lint ---------------------------------------------------------

def test_validate_exposition_accepts_real_pages():
    assert validate_exposition(HIST) == []


def test_validate_exposition_catches_violations():
    assert any("duplicate series" in e for e in validate_exposition(
        "# TYPE x counter\nx 1\nx 2\n"
    ))
    assert any("duplicate TYPE" in e for e in validate_exposition(
        "# TYPE x counter\n# TYPE x counter\nx 1\n"
    ))
    assert any("after its samples" in e for e in validate_exposition(
        "x 1\n# TYPE x counter\n"
    ))
    assert any("re-opened" in e for e in validate_exposition(
        "# TYPE a counter\n# TYPE b counter\na 1\nb 1\na{l=\"2\"} 2\n"
    ))
    assert any("bad label syntax" in e for e in validate_exposition(
        'm{key=unquoted} 1\n'
    ))
    assert any("le label" in e for e in validate_exposition(
        "# TYPE h histogram\nh_bucket 1\n"
    ))
    assert any("quantile" in e for e in validate_exposition(
        "# TYPE s summary\ns 1\n"
    ))


# -- prometheus-rules.yaml contract ------------------------------------------

def _rendered_sample_names() -> set:
    """Every sample name the two daemons' registries actually render,
    with all optional loops/telemetry attached."""
    from types import SimpleNamespace

    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import (
        render_extender_metrics,
        render_plugin_metrics,
    )
    from tpukube.obs.events import EventJournal
    from tpukube.obs.health import HealthSampler
    from tpukube.plugin import DevicePluginServer
    from tpukube.sched.extender import Extender

    # tenancy and capacity analytics on (with a quota'd tenant): the
    # tenant and capacity families are conditional series the tenancy
    # and capacity rules reference — the cross-check must see the
    # exposition such a deployment actually renders
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_QUOTAS": "teamA=chips:2,hbm:0.5",
        "TPUKUBE_CAPACITY_ENABLED": "1",
    })
    ext = Extender(cfg)
    ext.events.emit("GangCommitted", obj="gang/x")
    evictions = SimpleNamespace(
        depth=lambda: 0, evicted=0, blocked=0, failures=0,
        oldest_age_seconds=lambda now=None: 0.0,
    )
    reconcile = SimpleNamespace(reconciled=0)
    node_refresh = SimpleNamespace(refreshed=0)
    lifecycle = SimpleNamespace(released=0)
    text = render_extender_metrics(
        ext, reconcile=reconcile, evictions=evictions,
        node_refresh=node_refresh, lifecycle=lifecycle,
    )
    names = {s.name for s in parse_metrics(text)}

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        node_cfg = load_config(env={
            "TPUKUBE_DEVICE_PLUGIN_DIR": td,
            "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
            "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        })
        with TpuDeviceManager(node_cfg) as device, \
                DevicePluginServer(node_cfg, device) as server:
            journal = EventJournal()
            sampler = HealthSampler(device, journal=journal,
                                    poll_seconds=999)
            sampler.check_once()
            text = render_plugin_metrics(
                server, sampler=sampler, events=journal,
            )
    names |= {s.name for s in parse_metrics(text)}

    # the shard router's federated registry (ISSUE 16 federation
    # rules): the subprocess-gated transport telemetry only renders in
    # process mode, so duck-type a 2-replica process-mode router — the
    # registry itself is the real one, only the transports are stubs
    # (spawning worker daemons is not available everywhere this runs)
    from tpukube.metrics import render_router_metrics

    transport = SimpleNamespace(
        summary=lambda: {},
        rtt_snapshot=lambda: [0.001, 0.002],
        wire_snapshot=lambda: {
            "tx": 1, "rx": 1,
            "by_op": {"handle": {"tx": 1, "rx": 1}},
        },
        health_checks=1,
        health_failures=0,
    )
    router = SimpleNamespace(
        mode="subprocess",
        replicas=[
            SimpleNamespace(index=i, name=f"r{i}", alive=True,
                            killed=False, pods_routed=0,
                            transport=transport)
            for i in range(2)
        ],
        rendezvous_prepared=0, rendezvous_committed=0,
        rendezvous_aborted=0,
    )
    names |= {s.name for s in parse_metrics(render_router_metrics(router))}
    return names


def test_prometheus_rules_reference_only_rendered_series():
    """ISSUE 2 acceptance: every metric name in
    deploy/prometheus-rules.yaml expressions must be a series the
    registries actually render — a renamed series fails here instead of
    silently blinding the alerts."""
    with open(os.path.join(DEPLOY, "prometheus-rules.yaml")) as f:
        (doc,) = list(yaml.safe_load_all(f))
    assert doc["kind"] == "PrometheusRule"
    rendered = _rendered_sample_names()
    exprs = [
        rule["expr"]
        for group in doc["spec"]["groups"]
        for rule in group["rules"]
    ]
    assert exprs, "rules file must define rules"
    for expr in exprs:
        for name in referenced_metric_names(expr):
            assert name in rendered, (
                f"rule references {name!r}, which no registry renders; "
                f"expr: {expr}"
            )
    # the burn-rate rules encode the same thresholds DEFAULT_SLOS uses
    text = str(exprs)
    for slo in DEFAULT_SLOS:
        assert f'le="{slo.threshold_le}"' in text, slo.name


def test_slo_cli_snapshot_mode(tmp_path, capsys):
    import json

    from tpukube import cli

    snap = tmp_path / "metrics.txt"
    snap.write_text(
        HIST.replace("lat_seconds", "gang_schedule_latency_seconds")
    )
    rc = cli.main_obs(["slo", "--snapshot", str(snap)])
    assert rc == 0  # burn 1.0 does not page
    out = json.loads(capsys.readouterr().out)
    assert out["gang-schedule-latency"]["burn_rate"] == 1.0
    # no bind traffic in the snapshot: burn is None, not a crash
    assert out["bind-webhook-latency"]["burn_rate"] is None


def test_slo_cli_live_scrape():
    """`tpukube-obs slo --url` against a live extender /metrics — the
    acceptance path scenario 7 exercises via the library."""
    import io
    import json
    import sys

    from tpukube import cli
    from tpukube.core.types import PodGroup
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, group=group))
        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            rc = cli.main_obs(["slo", "--url", f"{c.base_url}/metrics"])
        finally:
            sys.stdout = stdout
    assert rc in (0, 1)  # 1 only if the sim run burned at page rate
    out = json.loads(buf.getvalue())
    gang = out["gang-schedule-latency"]
    assert gang["total"] >= 1
    assert gang["burn_rate"] is not None
    assert gang["window"] == "lifetime"

"""ISSUE 8: batched scheduling cycles + discrete-event fake clock.

The load-bearing contract is PARITY: with ``batch_enabled`` on, every
placement decision (node, chips, preemption plan, DCN split) must be
bit-identical to the legacy per-pod webhook path — batching may only
change how fast answers are computed, never what they are. The suite
proves it three ways: sequential webhook workloads (batch of 1 per
cycle), the batch driver vs sequential scheduling of the same pods,
and whole sim scenarios re-run under TPUKUBE_BATCH_ENABLED=1.
"""

from __future__ import annotations

import os

import pytest

from tpukube.core.clock import FakeClock, SystemClock
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim.harness import SimCluster

SMALL = {
    "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
    "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
}


def _cfg(batch: bool, **extra: str):
    env = dict(SMALL)
    env.update(extra)
    if batch:
        env["TPUKUBE_BATCH_ENABLED"] = "1"
    return load_config(env=env)


def _placement(alloc):
    return (alloc.node_name, tuple(sorted(alloc.device_ids)),
            tuple(sorted(tuple(c) for c in alloc.coords)))


# -- fake clock --------------------------------------------------------------

def test_fake_clock_advances_and_fires_timers_in_deadline_order():
    clock = FakeClock()
    fired = []
    clock.schedule(5.0, lambda: fired.append(("b", clock.monotonic())))
    clock.schedule(2.0, lambda: fired.append(("a", clock.monotonic())))
    clock.schedule(20.0, lambda: fired.append(("c", clock.monotonic())))
    clock.advance(10.0)
    # due timers fire in deadline order, each observing its own deadline
    assert fired == [("a", 2.0), ("b", 5.0)]
    assert clock.monotonic() == 10.0
    assert clock.pending_timers() == 1
    clock.sleep(15.0)  # sleep IS an advance
    assert fired[-1] == ("c", 20.0)
    assert clock.monotonic() == 25.0


def test_fake_clock_timer_scheduled_inside_window_fires_same_advance():
    clock = FakeClock()
    fired = []
    clock.schedule(1.0, lambda: clock.schedule(
        1.0, lambda: fired.append(clock.monotonic())))
    clock.advance(5.0)
    assert fired == [2.0]


def test_fake_clock_rejects_backwards_time_and_anchors_wall_clock():
    clock = FakeClock(epoch=1000.0)
    assert clock.time() == 1000.0
    clock.advance(3.0)
    assert clock.time() == 1003.0
    with pytest.raises(ValueError):
        clock.advance(-1.0)


def test_system_clock_is_real_time():
    clock = SystemClock()
    a = clock.monotonic()
    assert clock.monotonic() >= a


def test_harness_advance_requires_fake_clock():
    with SimCluster(_cfg(False), in_process=True) as c:
        with pytest.raises(RuntimeError, match="FakeClock"):
            c.advance(1.0)


def test_fake_clock_drives_gang_ttl_sweep():
    clock = FakeClock()
    cfg = _cfg(False, TPUKUBE_RESERVATION_TTL_SECONDS="30")
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        group = PodGroup("stuck", min_member=4)
        # one member filters (reservation created) but never binds
        c.make_pod("stuck-0", tpu=1, group=group)
        args, _ = c._extender_node_args()
        c._post("/filter", {"Pod": c.pods["default/stuck-0"], **args})
        assert len(c.extender.gang.snapshot()) == 1
        clock.advance(31.0)  # instant wall time, 31 simulated seconds
        c.extender.gang.sweep()
        assert c.extender.gang.snapshot() == []


# -- config knobs ------------------------------------------------------------

def test_batch_knobs_default_to_legacy_behavior():
    cfg = load_config(env={})
    assert cfg.batch_enabled is False
    assert cfg.batch_max_pods == 64
    assert cfg.cycle_interval_seconds == 0.0
    # and with batching off, nothing batch-related is constructed
    from tpukube.sched.extender import Extender

    assert Extender(cfg).cycle is None


def test_batch_knobs_coerce_from_env():
    cfg = load_config(env={
        "TPUKUBE_BATCH_ENABLED": "true",
        "TPUKUBE_BATCH_MAX_PODS": "128",
        "TPUKUBE_CYCLE_INTERVAL_SECONDS": "0.25",
    })
    assert cfg.batch_enabled is True
    assert cfg.batch_max_pods == 128
    assert cfg.cycle_interval_seconds == 0.25


def test_batch_knob_validation():
    with pytest.raises(ValueError, match="batch_max_pods"):
        load_config(env={"TPUKUBE_BATCH_MAX_PODS": "0"})
    with pytest.raises(ValueError, match="cycle_interval_seconds"):
        load_config(env={"TPUKUBE_CYCLE_INTERVAL_SECONDS": "-1"})


# -- placement parity: sequential webhook workloads --------------------------

def _run_mixed_workload(batch: bool):
    """The placement-relevant decision log of a workload exercising
    every planner arm: topology-scored singles, a multi-chip pod, vTPU
    shares, a gang, a preemption, churn releases."""
    cfg = _cfg(batch, TPUKUBE_SHARES_PER_CHIP="2")
    out = {}
    with SimCluster(cfg, vtpu_nodes={"host-0-1-0"}, vtpu_shares=2,
                    in_process=True) as c:
        for i in range(6):
            _, alloc = c.schedule(c.make_pod(f"s-{i}", tpu=1))
            out[f"s-{i}"] = _placement(alloc)
        _, alloc = c.schedule(c.make_pod("wide", tpu=4))
        out["wide"] = _placement(alloc)
        for i in range(2):
            _, alloc = c.schedule(c.make_pod(f"v-{i}", vtpu=1))
            out[f"v-{i}"] = _placement(alloc)
        # churn: a single completes, its chip is re-placed
        c.complete_pod("s-3")
        _, alloc = c.schedule(c.make_pod("refill", tpu=1))
        out["refill"] = _placement(alloc)
        # fill the rest, then a priority gang preempts its way in
        fill = 0
        while True:
            try:
                _, alloc = c.schedule(c.make_pod(f"f-{fill}", tpu=1))
                out[f"f-{fill}"] = _placement(alloc)
                fill += 1
            except RuntimeError:
                break
        group = PodGroup("boss", min_member=8)
        for i in range(8):
            _, alloc = c.schedule(
                c.make_pod(f"boss-{i}", tpu=1, priority=100, group=group)
            )
            out[f"boss-{i}"] = _placement(alloc)
        out["__preemptions"] = c.extender.preemptions
        out["__binds"] = c.extender.binds_total
        out["__util"] = c.utilization()
        out["__ledger"] = sorted(
            (a.pod_key, _placement(a))
            for a in c.extender.state.allocations()
        )
    return out


def test_mixed_workload_placements_bit_identical():
    legacy = _run_mixed_workload(batch=False)
    batched = _run_mixed_workload(batch=True)
    assert legacy == batched


def _run_dcn_workload(batch: bool):
    """DCN-split gang over two slices — the multi-slice planner arm."""
    from tpukube.core.mesh import MeshSpec

    env = {}
    if batch:
        env["TPUKUBE_BATCH_ENABLED"] = "1"
    cfg = load_config(env=env)
    slices = {
        "s0": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
        "s1": MeshSpec(dims=(2, 2, 2), host_block=(2, 2, 1)),
    }
    out = {}
    with SimCluster(cfg, slices=slices, in_process=True) as c:
        group = PodGroup("span", min_member=12, allow_dcn=True)
        for i in range(12):
            _, alloc = c.schedule(
                c.make_pod(f"span-{i}", tpu=1, group=group)
            )
            out[f"span-{i}"] = (_placement(alloc), dict(alloc.env))
        gangs = c.extender.gang_snapshot()
        out["__slices"] = gangs[0]["slices"]
        out["__spans_dcn"] = gangs[0]["spans_dcn"]
    return out


def test_dcn_split_gang_bit_identical():
    assert _run_dcn_workload(False) == _run_dcn_workload(True)


# -- placement parity: batch driver vs sequential ----------------------------

def test_batch_driver_matches_sequential_placements():
    """schedule_pending (one plan cycle for the whole batch, fast-path
    placements, binds consumed from the plan) must place every pod
    exactly where sequentially scheduling them in the same order
    would."""
    with SimCluster(_cfg(False), in_process=True) as c:
        sequential = {}
        for i in range(12):
            _, alloc = c.schedule(c.make_pod(f"p-{i}", tpu=1))
            sequential[f"default/p-{i}"] = _placement(alloc)
    with SimCluster(_cfg(True), in_process=True) as c:
        pods = [c.make_pod(f"p-{i}", tpu=1) for i in range(12)]
        batched = {
            key: _placement(alloc)
            for key, (_, alloc) in c.schedule_pending(pods).items()
        }
        stats = c.extender.cycle.stats()
        # genuinely batched: one cycle planned all twelve
        assert stats["cycles"] == 1
        assert stats["last_batch_size"] == 12
        assert stats["assume_undos"] == 0
    assert sequential == batched


def test_batch_driver_orders_by_priority_then_gang():
    """Queue order is (priority desc, gangs first, arrival): a
    high-priority gang admitted last still plans (and lands) before
    low-priority strays admitted first."""
    with SimCluster(_cfg(True), in_process=True) as c:
        strays = [c.make_pod(f"stray-{i}", tpu=1) for i in range(8)]
        group = PodGroup("vip", min_member=8)
        vips = [c.make_pod(f"vip-{i}", tpu=1, priority=50, group=group)
                for i in range(8)]
        c.schedule_pending(strays + vips)
        gangs = c.extender.gang_snapshot()
        assert gangs and gangs[0]["committed"]
        # the gang got a contiguous box (it planned against the empty
        # mesh, before the strays fragmented it)
        coords = [tuple(x) for cs in gangs[0]["slices"].values()
                  for x in cs]
        ex = [max(c_[a] for c_ in coords) - min(c_[a] for c_ in coords)
              + 1 for a in range(3)]
        assert ex[0] * ex[1] * ex[2] == len(coords) == 8


def test_batch_driver_raises_when_unschedulable():
    with SimCluster(_cfg(True), in_process=True) as c:
        pods = [c.make_pod(f"p-{i}", tpu=1) for i in range(33)]  # 32 chips
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule_pending(pods)
        # the 32 placeable pods landed; only the 33rd failed
        assert len(c.extender.state.allocations()) == 32


# -- plan consumption edge cases ---------------------------------------------

def test_bind_to_unplanned_node_undoes_assume_and_replans():
    """The scheduler disagreeing with the planned node (another
    extender's scores) must undo the assumed allocation and re-plan on
    the requested node — no double-booking, no leak."""
    with SimCluster(_cfg(True), in_process=True) as c:
        pod = c.make_pod("contrary", tpu=1)
        args, _ = c._extender_node_args()
        c._post("/filter", {"Pod": pod, **args})
        ext = c.extender
        planned = ext.planned_node("default/contrary")
        assert planned is not None
        other = next(n for n in ext.state.node_names() if n != planned)
        bres = c._post("/bind", {
            "PodName": "contrary", "PodNamespace": "default",
            "PodUID": pod["metadata"]["uid"], "Node": other,
        })
        assert not bres.get("Error")
        alloc = ext.state.allocation("default/contrary")
        assert alloc is not None and alloc.node_name == other
        assert ext.binds_total == 1  # the undo reversed the assume's count
        assert ext.cycle.assume_undos == 1
        # exactly one allocation exists — the assume did not leak
        assert len(ext.state.allocations()) == 1


def test_release_before_bind_unwinds_assumed_plan():
    """A pod deleted between its filter (plan + assume) and its bind
    must leave no ledger entry and no bind count."""
    with SimCluster(_cfg(True), in_process=True) as c:
        pod = c.make_pod("ghost", tpu=1)
        args, _ = c._extender_node_args()
        c._post("/filter", {"Pod": pod, **args})
        ext = c.extender
        assert ext.state.allocation("default/ghost") is not None  # assumed
        c.delete_pod("ghost")
        assert ext.state.allocation("default/ghost") is None
        assert ext.binds_total == 0
        assert ext.cycle.planned_node("default/ghost") is None


def test_assumed_plan_expires_on_reservation_ttl():
    clock = FakeClock()
    cfg = _cfg(True, TPUKUBE_RESERVATION_TTL_SECONDS="30")
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        pod = c.make_pod("abandoned", tpu=1)
        args, _ = c._extender_node_args()
        c._post("/filter", {"Pod": pod, **args})
        ext = c.extender
        assert ext.state.allocation("default/abandoned") is not None
        clock.advance(31.0)
        # any later cycle sweeps the expired assume
        c.schedule(c.make_pod("later", tpu=1))
        assert ext.state.allocation("default/abandoned") is None
        assert ext.cycle.assume_undos == 1


def test_unschedulable_plans_expire_instead_of_accumulating():
    """A stream of never-binding infeasible pods with unique names must
    not grow the plan table without bound (the daemon-OOM shape)."""
    clock = FakeClock()
    cfg = _cfg(True, TPUKUBE_RESERVATION_TTL_SECONDS="30")
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        # fill the mesh so every further pod plans unschedulable
        pods = [c.make_pod(f"f-{i}", tpu=1) for i in range(32)]
        c.schedule_pending(pods)
        for i in range(10):
            with pytest.raises(RuntimeError, match="unschedulable"):
                c.schedule(c.make_pod(f"nope-{i}", tpu=1), retries=1)
        assert len(c.extender.cycle._plans) >= 10
        clock.advance(31.0)
        with pytest.raises(RuntimeError, match="unschedulable"):
            c.schedule(c.make_pod("one-more", tpu=1), retries=1)
        # the TTL janitor swept the stale unschedulable entries
        assert len(c.extender.cycle._plans) <= 1


def test_batch_mode_records_one_latency_sample_per_webhook():
    """Plan-time internal filter/prioritize/bind calls must not feed
    the webhook histograms: one webhook, one sample — same cardinality
    as legacy mode, so the dashboarded p99 stays comparable."""
    def counts(batch):
        with SimCluster(_cfg(batch), in_process=True) as c:
            for i in range(3):
                c.schedule(c.make_pod(f"p-{i}", tpu=1))
            group = PodGroup("g", min_member=2)
            for i in range(2):
                c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=5,
                                      group=group))
            return {h: len(w) for h, w in c.extender.latencies.items()}

    assert counts(batch=True) == counts(batch=False) == {
        "filter": 5, "prioritize": 5, "bind": 5,
    }


def test_queued_pods_plan_against_their_own_candidate_sets():
    """A webhook-triggered drain must not plan driver-admitted pods
    against the webhook's (possibly restricted) node list: driver pods
    place cluster-wide."""
    from tpukube.sched import kube

    with SimCluster(_cfg(True), in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        # driver-admit two pods, then a webhook pod arrives carrying a
        # TWO-node candidate list and triggers the drain
        for i in range(2):
            ext.admit(kube.pod_from_k8s(c.make_pod(f"drv-{i}", tpu=1)))
        # the wire carries a JSON array: node_names() itself serves a
        # cached tuple (ISSUE 11 satellite), so listify for the body
        restricted = list(ext.state.node_names()[:2])
        probe = c.make_pod("probe", tpu=1)
        fres = ext.handle("filter", {"Pod": probe,
                                     "NodeNames": restricted})
        assert fres["NodeNames"]  # probe feasible within its two nodes
        assert set(fres["NodeNames"]) <= set(restricted)
        # driver pods were planned against EVERY node, not the probe's
        # two — and assumed allocations landed for all three
        for i in range(2):
            assert ext.planned_node(f"default/drv-{i}") is not None
        assert len(ext.state.allocations()) == 3


def test_duplicate_filter_is_a_plan_hit_with_identical_answer():
    with SimCluster(_cfg(True), in_process=True) as c:
        pod = c.make_pod("dup", tpu=1)
        args, _ = c._extender_node_args()
        first = c._post("/filter", {"Pod": pod, **args})
        args2, _ = c._extender_node_args()  # names-only now
        second = c._post("/filter", {"Pod": pod, **args2})
        assert first["NodeNames"] == second["NodeNames"]
        assert first["FailedNodes"] == second["FailedNodes"]
        assert len(c.extender.state.allocations()) == 1  # one assume


# -- observability -----------------------------------------------------------

def test_cycle_metrics_and_statusz_render_only_when_batching():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    with SimCluster(_cfg(True), in_process=True) as c:
        c.schedule(c.make_pod("m-0", tpu=1))
        text = render_extender_metrics(c.extender)
        for series in ("tpukube_cycles_total", "tpukube_cycle_plan_hits_total",
                       "tpukube_cycle_pods_planned_total",
                       "tpukube_cycle_wall_seconds_bucket",
                       "tpukube_cycle_queue_depth"):
            assert series in text, series
        doc = extender_statusz(c.extender)
        cyc = doc["cycle"]
        assert cyc["enabled"] and cyc["pods_planned"] == 1
        assert cyc["plan_hit_ratio"] is not None
    with SimCluster(_cfg(False), in_process=True) as c:
        c.schedule(c.make_pod("m-0", tpu=1))
        text = render_extender_metrics(c.extender)
        assert "tpukube_cycle" not in text  # legacy exposition untouched
        assert extender_statusz(c.extender)["cycle"] == {"enabled": False}


# -- scenario-level parity ---------------------------------------------------

def _scenario_result(n: int, batch: bool, keys):
    from tpukube.sim import scenarios

    old = os.environ.pop("TPUKUBE_BATCH_ENABLED", None)
    try:
        if batch:
            os.environ["TPUKUBE_BATCH_ENABLED"] = "1"
        r = scenarios.run(n)
    finally:
        os.environ.pop("TPUKUBE_BATCH_ENABLED", None)
        if old is not None:
            os.environ["TPUKUBE_BATCH_ENABLED"] = old
    return {k: r[k] for k in keys}


#: per-scenario placement-relevant result keys (timing keys excluded —
#: parity is about decisions, not wall clock)
SCENARIO_KEYS = {
    1: ("node", "devices", "env_keys", "utilization_percent"),
    2: ("placements", "utilization_percent"),
    3: ("pods", "shared_one_chip"),
    4: ("gang_box", "contiguous", "utilization_percent"),
    5: ("value", "vs_baseline", "preemptions", "pods_placed"),
    6: ("value", "waves", "wave_size", "full_utilization_percent",
        "util_min_after_refill_percent", "lifecycle_releases"),
}


@pytest.mark.parametrize("scenario", sorted(SCENARIO_KEYS))
def test_scenario_placements_bit_identical_with_batching(scenario):
    keys = SCENARIO_KEYS[scenario]
    legacy = _scenario_result(scenario, False, keys)
    batched = _scenario_result(scenario, True, keys)
    assert legacy == batched, f"scenario {scenario} diverged"


def test_chaos_scenarios_green_with_batching():
    """Scenarios 8 (apiserver chaos + degraded mode) and 9 (crash
    recovery) raise on any invariant violation — green under batching
    means assumes never leak through fault injection, effector undo,
    or a cold restart."""
    from tpukube.sim import scenarios

    old = os.environ.pop("TPUKUBE_BATCH_ENABLED", None)
    try:
        os.environ["TPUKUBE_BATCH_ENABLED"] = "1"
        r8 = scenarios.run(8)
        assert r8["leaked_reservations"] == 0
        assert r8["ledger_divergence"] == 0
        assert r8["blackout_refused"] and r8["degraded_refusals"] > 0
        r9 = scenarios.run(9)
        assert r9["gang_committed"]
        assert r9["leaked_reservations"] == 0
        assert r9["ledger_divergence"] == 0
    finally:
        os.environ.pop("TPUKUBE_BATCH_ENABLED", None)
        if old is not None:
            os.environ["TPUKUBE_BATCH_ENABLED"] = old


def test_chaos_batch_burst_converges_clean():
    """A short seeded chaos burst straight at the batch path (torn
    binds, 410s, transport errors against assumed allocations) must
    converge with zero leaks — the targeted arm of the scenario-8
    contract above."""
    from tpukube.chaos import (
        ChaosSimCluster, ChaosSpec, FaultSchedule, converge,
        leaked_reservations, ledger_divergence,
    )

    cfg = _cfg(True)
    spec = ChaosSpec(error_rate=0.15, torn_rate=0.1, gone_rate=0.1)
    with ChaosSimCluster(cfg, FaultSchedule(7, spec)) as c:
        placed = 0
        for i in range(12):
            try:
                c.schedule(c.make_pod(f"cb-{i}", tpu=1))
                placed += 1
            except RuntimeError:
                pass
        converge(c)
        assert placed > 0
        assert leaked_reservations(c) == []
        assert ledger_divergence(c) == []


# -- kilonode scenario (scaled down for tier-1) ------------------------------

def test_kilonode_scenario_smoke(monkeypatch):
    """Scenario 10 at a tier-1-friendly scale: 1024 nodes, ~1.5k pods,
    fake clock. The full 8k/100k-pod runs live in tools/check.sh and
    bench.py; this asserts the machinery (batch driver at 1k nodes,
    webhook sampling, ledger convergence, time compression) end to
    end."""
    from tpukube.sim import scenarios

    monkeypatch.setenv("TPUKUBE_KILONODE_PODS", "1500")
    monkeypatch.delenv("TPUKUBE_BATCH_ENABLED", raising=False)
    r = scenarios.run(10)
    assert r["nodes"] == 1024 and r["chips"] == 4096
    assert r["pods_total"] == 1500
    assert r["gang_committed"]
    assert r["ledger_divergence"] == 0
    assert r["pods_sampled_full_protocol"] > 0
    assert r["cycle"]["plan_hit_ratio"] > 0.9
    assert r["time_compression"] > 1.0
    assert set(r["webhook_p99_ms"]) == {"filter", "prioritize", "bind"}


# -- ISSUE 10: persistent fast state + batched gang planning -----------------

def _run_waves(delta: bool):
    """Three schedule_pending waves with completion churn between them
    — the shape whose per-cycle fast-state rebuild ISSUE 10 removes.
    ``delta=False`` forces the rebuild-every-cycle oracle (no delta
    chain to patch from)."""
    cfg = _cfg(True, TPUKUBE_SNAPSHOT_DELTA_ENABLED="1" if delta
               else "0")
    placements = {}
    with SimCluster(cfg, clock=FakeClock(), in_process=True) as c:
        seq = 0
        alive = []
        for wave in range(3):
            pods = []
            for _ in range(8):
                pods.append(c.make_pod(f"w-{seq}", tpu=1))
                alive.append(f"w-{seq}")
                seq += 1
            for key, (_, alloc) in c.schedule_pending(pods).items():
                placements[key] = _placement(alloc)
            c.advance(60.0)
            done, alive = alive[:8], alive[8:]
            for name in done:
                c.pods.pop(f"default/{name}", None)
            c._lifecycle.check_once()
        stats = c.extender.cycle.stats()
        placements["__ledger"] = sorted(
            (a.pod_key, _placement(a))
            for a in c.extender.state.allocations()
        )
    return placements, stats


def test_fast_state_persists_patches_and_places_identically():
    """The overlay survives across cycles and is patched O(Δ) from the
    delta chain — and every placement matches the rebuild-every-cycle
    oracle bit for bit."""
    oracle, o_stats = _run_waves(delta=False)
    live, l_stats = _run_waves(delta=True)
    assert oracle == live
    # the oracle cannot patch (no delta log): every advance rebuilds
    assert o_stats["fast_patches"] == 0
    # the live run built once and patched the overlay thereafter
    assert l_stats["fast_rebuilds"] == 1
    assert l_stats["fast_patches"] >= 2  # waves 2 and 3 saw releases


def _run_gang_drive(via_driver: bool):
    """One 8-member gang + bystanders through the batch driver (the
    batched gang arm) vs sequential per-pod webhooks (the legacy
    path). Placements must agree member for member."""
    cfg = _cfg(True)
    out = {}
    with SimCluster(cfg, in_process=True) as c:
        for i in range(3):
            _, alloc = c.schedule(c.make_pod(f"bg-{i}", tpu=1))
            out[f"bg-{i}"] = _placement(alloc)
        group = PodGroup("band", min_member=8)
        pods = [c.make_pod(f"band-{i}", tpu=1, priority=10, group=group)
                for i in range(8)]
        if via_driver:
            for key, (_, alloc) in c.schedule_pending(pods).items():
                out[key.split("/", 1)[1]] = _placement(alloc)
            stats = c.extender.cycle.stats()
            assert stats["gang_batches"] >= 1
            assert stats["gang_batch_members"] == 8
        else:
            for obj in pods:
                _, alloc = c.schedule(obj)
                out[obj["metadata"]["name"]] = _placement(alloc)
        gangs = c.extender.gang_snapshot()
        out["__committed"] = [g["group"] for g in gangs
                              if g["committed"]]
        out["__ledger"] = sorted(
            (a.pod_key, _placement(a))
            for a in c.extender.state.allocations()
        )
    return out


def test_gang_batch_arm_matches_sequential_webhooks():
    assert _run_gang_drive(via_driver=False) == \
        _run_gang_drive(via_driver=True)


def test_gang_batch_arm_defers_preemption_to_general_path():
    """A gang that needs preemption must leave the batched arm: the
    two-phase plan (victims deferred to first bind) belongs to the
    legacy path, and the driver still converges through requeues."""
    cfg = _cfg(True)
    with SimCluster(cfg, clock=FakeClock(), in_process=True) as c:
        fill = 0
        while True:
            try:
                c.schedule(c.make_pod(f"f-{fill}", tpu=1))
                fill += 1
            except RuntimeError:
                break
        group = PodGroup("usurper", min_member=8)
        pods = [c.make_pod(f"u-{i}", tpu=1, priority=100, group=group)
                for i in range(8)]
        c.schedule_pending(pods, retries=8)
        gangs = c.extender.gang_snapshot()
        assert any(g["group"] == "usurper" and g["committed"]
                   for g in gangs)
        assert c.extender.preemptions > 0
        # while victims were pending/terminating the arm fell back to
        # the general path (two-phase preemption executes at a real
        # bind); once the reservation is clean, later requeue rounds
        # may batch the remaining members — both routes bind through
        # the same Extender.bind, so the commit above is the contract

"""ResNet workload (BASELINE config 2's model family) on the virtual
8-device CPU mesh, mirroring the Llama tests' structure."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukube.workload.meshenv import (
    ENV_GANG_NUM_SLICES,
    ENV_GANG_SLICE_INDEX,
    ENV_GANG_SLICES,
    PodTpuEnv,
    build_multislice_mesh,
)
from tpukube.workload.resnet import (
    ResNetConfig,
    forward,
    init_params,
    loss_fn,
    make_dp_train_step,
)

TINY = ResNetConfig(num_classes=10, width=8, stage_blocks=(1, 1), groups=4,
                    image_size=8)


def _batch(n=4, cfg=TINY, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    images = jax.random.normal(k1, (n, cfg.image_size, cfg.image_size, 3))
    labels = jax.random.randint(k2, (n,), 0, cfg.num_classes)
    return images, labels


def test_forward_shapes_and_dtype():
    params = init_params(jax.random.PRNGKey(0), TINY)
    images, _ = _batch(3)
    logits = forward(params, images, TINY)
    assert logits.shape == (3, TINY.num_classes)
    assert logits.dtype == jnp.float32  # accumulate/classify in f32


def test_bottleneck_variant():
    cfg = ResNetConfig(num_classes=5, width=8, stage_blocks=(1, 1),
                       bottleneck=True, groups=4, image_size=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    logits = forward(params, _batch(2, cfg)[0], cfg)
    assert logits.shape == (2, 5)


def test_downsampling_halves_spatial():
    # stage 1 strides: 8x8 -> 4x4 before pooling; just assert it runs and
    # the head sees the doubled width
    cfg = TINY
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["head"].shape[0] == cfg.stage_width(len(cfg.stage_blocks) - 1)


def test_dp_loss_decreases():
    from jax.sharding import Mesh

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs), ("dp",))
    params = init_params(jax.random.PRNGKey(0), TINY)
    step = make_dp_train_step(TINY, mesh, learning_rate=0.05)
    images, labels = _batch(8)
    losses = []
    for _ in range(5):
        params, loss = step(params, images, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_dp_matches_single_device():
    from jax.sharding import Mesh

    params = init_params(jax.random.PRNGKey(0), TINY)
    images, labels = _batch(8)
    single = float(loss_fn(params, images, labels, TINY))
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    step = make_dp_train_step(TINY, mesh)
    _, sharded_loss = step(jax.tree_util.tree_map(jnp.copy, params),
                           images, labels)
    assert abs(float(sharded_loss) - single) < 1e-2  # bf16 tolerance


def test_pod_env_gang_slice_context():
    env = {
        "TPU_VISIBLE_DEVICES": "0",
        "TPU_KUBE_DEVICE_IDS": "tpu-0",
        "TPU_KUBE_CHIP_COORDS": "0,0,0",
        "TPU_KUBE_MESH_DIMS": "4,4,1",
        "TPU_KUBE_SLICE_ID": "slice-b",
        ENV_GANG_NUM_SLICES: "2",
        ENV_GANG_SLICES: "slice-a,slice-b",
        ENV_GANG_SLICE_INDEX: "1",
    }
    pe = PodTpuEnv.from_env(env)
    assert pe.spans_dcn
    assert pe.slice_id == "slice-b"
    assert pe.gang_slices == ("slice-a", "slice-b")
    assert pe.gang_slice_index == 1
    # absent gang env -> single-slice defaults
    for k in (ENV_GANG_NUM_SLICES, ENV_GANG_SLICES, ENV_GANG_SLICE_INDEX):
        env.pop(k)
    pe2 = PodTpuEnv.from_env(env)
    assert not pe2.spans_dcn and pe2.gang_num_slices == 1


def test_multislice_mesh_axes():
    mesh = build_multislice_mesh(jax.devices(), num_slices=2, dp=2, tp=2)
    assert mesh.axis_names == ("dcn", "dp", "tp")
    assert mesh.devices.shape == (2, 2, 2)


def test_multislice_dp_step_runs():
    """A DCN-spanning DP step: batch sharded over ('dcn','dp'), params
    replicated — the multislice pattern the DCN gang env describes."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_multislice_mesh(jax.devices(), num_slices=2, dp=4, tp=1)
    # fold tp=1 away: batch over both dcn and dp
    params = init_params(jax.random.PRNGKey(0), TINY)
    batch_spec = NamedSharding(mesh, P(("dcn", "dp")))
    repl = NamedSharding(mesh, P())

    @partial(jax.jit, in_shardings=(repl, batch_spec, batch_spec),
             out_shardings=(repl, None))
    def step(params, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels, TINY)
        return jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params,
                                      grads), loss

    images, labels = _batch(16)
    with mesh:
        params2, loss = step(params, images, labels)
    assert jnp.isfinite(loss)

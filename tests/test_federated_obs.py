"""ISSUE 16: federated observability plane over the sharded control
plane — cross-replica trace propagation, stitched /explain, aggregated
/metrics, and wire-cost accounting.

The acceptance gates covered here:
  * a DCN gang scheduled over the REAL subprocess transport yields a
    stitched /explain chain naming both parts, their replicas, and the
    rendezvous verdict;
  * the router's federated /metrics (worker registries merged under a
    ``replica`` label + router-local series) passes promlint;
  * the merged Chrome trace stitches the router's fan-out spans and
    both workers' captures on one clock, joined by propagated trace
    context;
  * ``shard_transport: inprocess`` at N=1 keeps the exposition
    byte-identical to the sole extender's own (off-is-off);
plus the satellites: /events federation with replica attribution and
the router's observability HTTP listener.

Worker daemons are real subprocesses; tests that need them skip
gracefully where spawning is unavailable.
"""

from __future__ import annotations

import json
import socket
import urllib.request

import pytest

from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import PodGroup
from tpukube.metrics import render_extender_metrics, render_federated_metrics
from tpukube.obs.events import filter_events, format_event
from tpukube.obs.slo import parse_metrics, validate_exposition
from tpukube.obs.timeline import merged_chrome_trace
from tpukube.sched.shard import ShardRouter
from tpukube.sim.harness import SimCluster

from tests.test_shard_proc import needs_workers


def obs_config(n: int = 2, **extra: str):
    """2 subprocess planner replicas with decision provenance fully on
    (sampling 1.0) — the federated-observability acceptance shape."""
    return load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_SHARD_TRANSPORT": "subprocess",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_DECISIONS_ENABLED": "1",
        "TPUKUBE_DECISIONS_SAMPLE_RATE": "1.0",
        **extra,
    })


def two_slices(dims=(2, 2, 2)) -> dict[str, MeshSpec]:
    return {
        sid: MeshSpec(dims=dims, host_block=(2, 2, 1),
                      torus=(False, False, False))
        for sid in ("s0", "s1")
    }


def _fill_and_rendezvous(c: SimCluster) -> None:
    """Commit one 4-member gang into each slice, then an 8-member
    DCN gang that can only place via the two-phase rendezvous."""
    for g in ("fill-a", "fill-b"):
        grp = PodGroup(g, min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"{g}-{i}", tpu=1, group=grp))
    dcn = PodGroup("dcn", min_member=8, allow_dcn=True)
    for i in range(8):
        c.schedule(c.make_pod(f"dcn-{i}", tpu=1, group=dcn,
                              priority=50))


# -- N=1 parity: off-is-off --------------------------------------------------

def test_n1_federated_exposition_is_sole_extender_verbatim():
    """At planner_replicas=1 the federated renderer IS the sole
    extender's renderer — byte-identical, no replica labels, no router
    series, and no router-side observability state at all."""
    router = ShardRouter(load_config(env={}))
    assert router._sole is not None
    text = render_federated_metrics(router)
    assert text == render_extender_metrics(router._sole)
    assert 'replica="' not in text
    assert "tpukube_router_wire_bytes_total" not in text
    # the router-side obs plane never initializes in sole mode
    assert router.trace is None
    assert router.decisions is None


# -- federated /metrics ------------------------------------------------------

@needs_workers
def test_federated_metrics_two_replicas_lint_clean():
    """The merged exposition after real cross-replica activity (two
    committed fill gangs + a DCN rendezvous gang) passes promlint and
    carries both replicas' series under the replica label plus the
    router-local wire counter."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        text = render_federated_metrics(router)
    errors = validate_exposition(text)
    assert errors == [], "\n".join(errors)
    assert 'replica="r0"' in text and 'replica="r1"' in text
    names = {s.name for s in parse_metrics(text)}
    # router-local series
    assert "tpukube_replica_up" in names
    assert "tpukube_router_wire_bytes_total" in names
    # worker-side series federate under the replica label (both
    # replicas really bound pods in this drive)
    binds = [s for s in parse_metrics(text)
             if s.name == "tpukube_binds_total"]
    assert {s.label("replica") for s in binds} >= {"r0", "r1"}


@needs_workers
def test_wire_accounting_and_flight_recorder():
    """Every fanned call is billed: the transport's wire counters are
    non-zero in both directions for the webhook op, per-replica totals
    cover both workers, and the bounded flight recorder holds the
    recent calls with op/replica/bytes/rtt."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        wt = router.wire_totals()
        assert wt["tx"] > 0 and wt["rx"] > 0
        assert wt["total"] == wt["tx"] + wt["rx"]
        assert set(wt["per_replica"]) == {"r0", "r1"}
        assert "handle" in wt["by_op"]
        flights = router.flights_snapshot()
        assert flights, "flight recorder is empty after real traffic"
        for f in flights:
            assert f["replica"] in ("r0", "r1")
            assert f["tx_bytes"] >= 0 and f["rx_bytes"] >= 0
        # the wire bill and flights surface on /statusz (statusz's own
        # summary fan-out is itself billed, so the total only grows)
        doc = router.statusz()
        assert doc["wire"]["total"] >= wt["total"]
        assert doc["flights"]


# -- stitched /explain -------------------------------------------------------

@needs_workers
def test_dcn_gang_stitched_explain_cites_both_replicas():
    """The federated chain for one DCN gang member, assembled over the
    real subprocess transport, names both parts, both replicas, and
    the rendezvous verdict — the ISSUE 16 acceptance sentence."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        doc = router.explain("default/dcn-0")
    assert doc is not None and doc["pod"] == "default/dcn-0"
    assert doc["verdict"] == "placed"
    # the chain carries router stages AND the owning replica's stages
    cited = {ev.get("replica") for ev in doc["stages"]}
    assert "router" in cited
    assert cited & {"r0", "r1"}
    stages = {ev.get("stage") for ev in doc["stages"]}
    assert "route" in stages and "rendezvous" in stages
    # the rendezvous verdict names both parts with their replicas
    rdv = [ev for ev in doc["stages"] if ev.get("stage") == "rendezvous"]
    assert any(ev.get("outcome") == "committed" for ev in rdv)
    parts = {(p["replica"], p["slice"])
             for ev in rdv for p in (ev.get("parts") or [])}
    assert parts == {("r0", "s0"), ("r1", "s1")}
    why = "\n".join(doc["why"])
    assert "DCN rendezvous committed for gang default/dcn" in why
    assert "replica r0" in why and "replica r1" in why


@needs_workers
def test_stitched_explain_resolves_bare_names_and_plain_pods():
    """A non-gang pod's federated chain still stitches (route stage +
    owning replica's webhook stages), and a bare pod name resolves in
    the default namespace — the `tpukube-obs explain --url <router>`
    contract."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        c.schedule(c.make_pod("solo", tpu=1))
        router = c.extender
        doc = router.explain("solo")
        assert doc is not None and doc["pod"] == "default/solo"
        assert doc["verdict"] == "placed"
        stages = {ev.get("stage") for ev in doc["stages"]}
        assert "route" in stages
        # batch-mode worker provenance: the cycle planned it, then bound
        assert {"cycle_plan", "bind"} <= stages
        # seqs are reassigned contiguously after the merge
        seqs = [ev["seq"] for ev in doc["stages"]]
        assert seqs == list(range(1, len(seqs) + 1))
        assert router.explain("default/never-seen") is None


# -- merged timeline ---------------------------------------------------------

@needs_workers
def test_merged_timeline_joins_router_and_worker_captures():
    """One Chrome trace from three captures (router + both workers):
    each capture is its own process, the router's fan-out spans render
    as explicit-bounds slices, and worker events carry the propagated
    trace context that joins them to the router's spans."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        assert router.trace is not None
        captures = [("router", router.trace.events())]
        for rep in router.replicas:
            captures.append((rep.name, rep.transport.trace_events()))
    merged = merged_chrome_trace(captures)
    evs = merged["traceEvents"]
    procs = {e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert procs == {"router", "r0", "r1"}
    router_traces = {e["args"].get("trace") for e in evs
                     if e.get("ph") == "X" and e["pid"] == 1}
    worker_traces = {e["args"].get("trace") for e in evs
                     if e.get("ph") == "X" and e["pid"] > 1}
    joined = (router_traces - {None}) & (worker_traces - {None})
    assert joined, "no propagated trace id joins router and workers"
    # router span slices carry true wall-clock bounds (dur from t0/t1)
    spans = [e for e in evs if e.get("ph") == "X" and e["pid"] == 1]
    assert spans and all(e["dur"] >= 1.0 for e in spans)


# -- federated /events -------------------------------------------------------

@needs_workers
def test_events_federated_with_replica_attribution():
    """/events merges the worker journals: every row is stamped with
    its source replica, the --replica filter narrows to one journal,
    and the human rendering shows the attribution."""
    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        evs = router.events_federated()
        assert evs
        assert {ev.get("replica") for ev in evs} == {"r0", "r1"}
        committed = router.events_federated(reason="GangCommitted")
        assert {ev["reason"] for ev in committed} == {"GangCommitted"}
        only0 = router.events_federated(replica="r0")
        assert only0 and {ev["replica"] for ev in only0} == {"r0"}
        assert filter_events(evs, replica="r0") == only0
        line = format_event(only0[0])
        assert line.endswith("@r0")
        # single-planner events (no attribution) never match a
        # replica filter
        assert filter_events([{"reason": "GangCommitted"}],
                             replica="r0") == []


# -- the router's observability listener -------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


@needs_workers
def test_router_obs_listener_serves_federated_views():
    """make_router_app over a live 2-replica plane: /metrics lints
    clean over HTTP, /explain answers the stitched chain, /events
    honors the replica filter, and /statusz carries the wire bill."""
    from tpukube.sched.extender import run_probe_server
    from tpukube.sched.shardworker import make_router_app

    cfg = obs_config()
    with SimCluster(cfg, clock=FakeClock(), in_process=True,
                    slices=two_slices()) as c:
        _fill_and_rendezvous(c)
        router = c.extender
        port = _free_port()
        stop = run_probe_server(make_router_app(router),
                                "127.0.0.1", port)
        try:
            base = f"http://127.0.0.1:{port}"
            assert _get(f"{base}/healthz") == "ok"
            text = _get(f"{base}/metrics")
            assert validate_exposition(text) == []
            assert 'replica="r0"' in text and 'replica="r1"' in text
            doc = json.loads(_get(f"{base}/explain?pod=default/dcn-0"))
            assert doc["verdict"] == "placed"
            assert any(ev.get("stage") == "rendezvous"
                       for ev in doc["stages"])
            evs = json.loads(_get(f"{base}/events?replica=r1"))
            assert evs and {ev["replica"] for ev in evs} == {"r1"}
            stz = json.loads(_get(f"{base}/statusz"))
            assert stz["sharded"] is True
            assert stz["wire"]["total"] > 0
            assert json.loads(_get(f"{base}/trace"))
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/explain")
            assert ei.value.code == 400
        finally:
            stop()

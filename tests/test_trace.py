"""Decision trace + replay (SURVEY.md §6 tracing) and /state endpoints."""

import json
import threading
import urllib.request

import pytest

from tpukube import trace as trace_mod
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    """One scheduling session — mixed plain pods, a gang, a delete —
    recorded to both the ring and a JSONL sink."""
    path = str(tmp_path_factory.mktemp("trace") / "decisions.jsonl")
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_TRACE_PATH": path,
    })
    with SimCluster(cfg) as c:
        for i in range(3):
            c.schedule(c.make_pod(f"plain-{i}", tpu=1))
        c.delete_pod("plain-1")
        group = PodGroup("g1", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"gang-{i}", tpu=1, priority=10, group=group))
        yield c, cfg, path


def test_trace_records_protocol_stream(traced_cluster):
    c, _, _ = traced_cluster
    events = c.extender.trace.events()
    kinds = [e["kind"] for e in events]
    # 7 scheduled pods -> at least 7 of each webhook; 1 release
    assert kinds.count("filter") >= 7
    assert kinds.count("prioritize") >= 7
    assert kinds.count("bind") >= 7
    assert kinds.count("release") == 1
    assert [e["seq"] for e in events] == list(range(1, len(events) + 1))
    # requests/responses are the verbatim webhook JSON
    first_bind = next(e for e in events if e["kind"] == "bind")
    assert "PodName" in first_bind["request"]
    assert first_bind["response"]["Error"] == ""


def test_replay_reproduces_decisions(traced_cluster):
    c, cfg, _ = traced_cluster
    divergences = trace_mod.replay(c.extender.trace.events(), config=cfg)
    assert divergences == []


def test_replay_detects_divergence(traced_cluster):
    c, cfg, _ = traced_cluster
    events = [dict(e) for e in c.extender.trace.events()]
    victim = next(e for e in events if e["kind"] == "bind")
    victim["response"] = dict(victim["response"],
                              Annotations={"tpu.qiniu.com/alloc": "{}"})
    divergences = trace_mod.replay(events, config=cfg)
    assert len(divergences) == 1
    assert divergences[0].kind == "bind"
    assert divergences[0].seq == victim["seq"]
    assert "divergence at seq" in str(divergences[0])


def test_jsonl_sink_round_trips(traced_cluster):
    c, cfg, path = traced_cluster
    loaded = trace_mod.load(path)
    live = c.extender.trace.events()
    assert [e["seq"] for e in loaded] == [e["seq"] for e in live]
    assert trace_mod.replay(loaded, config=cfg) == []


def test_state_endpoints(traced_cluster):
    c, _, _ = traced_cluster
    topo = _get(f"{c.base_url}/state/topology")
    assert topo["mesh_dims"] == [4, 4, 1]
    assert topo["chips_total"] == 16
    # 2 plain survivors + 4 gang members
    assert topo["chips_allocated"] == 6
    statuses = {
        ch["status"] for n in topo["nodes"] for ch in n["chips"]
    }
    assert statuses == {"allocated", "free"}

    allocs = _get(f"{c.base_url}/state/allocs")
    assert len(allocs) == 6
    assert all(a["devices"] for a in allocs)
    assert not any(a["pod"].endswith("plain-1") for a in allocs)

    gangs = _get(f"{c.base_url}/state/gangs")
    assert len(gangs) == 1
    assert gangs[0]["group"] == "g1"
    assert gangs[0]["committed"] is True
    assert gangs[0]["members_bound"] == 4
    assert gangs[0]["spans_dcn"] is False
    (slice_chips,) = gangs[0]["slices"].values()
    assert len(slice_chips) == 4


def test_trace_endpoint_incremental(traced_cluster):
    c, _, _ = traced_cluster
    all_events = _get(f"{c.base_url}/trace")
    assert [e["seq"] for e in all_events] == [
        e["seq"] for e in c.extender.trace.events()
    ]
    mid = all_events[len(all_events) // 2]["seq"]
    later = _get(f"{c.base_url}/trace?since={mid}")
    assert [e["seq"] for e in later] == [
        e["seq"] for e in all_events if e["seq"] > mid
    ]


def test_threaded_capture_replays_clean():
    """Mutation + trace record are ONE atomic step: a trace captured while
    schedules (webhook loop) and releases (a different thread) interleave
    must still replay with zero divergences — trace order is application
    order, not just webhook-stream order."""
    import threading

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for i in range(8):
            c.schedule(c.make_pod(f"seed-{i}", tpu=1))

        errs: list[BaseException] = []

        def run(fn):
            try:
                fn()
            except BaseException as e:  # surfaced after join
                errs.append(e)

        def churn_release():
            for i in range(8):
                c.delete_pod(f"seed-{i}")

        def churn_schedule():
            # 8 seeds + 8 late = 16 chips: fits even if no release lands
            for i in range(8):
                c.schedule(c.make_pod(f"late-{i}", tpu=1))

        threads = [
            threading.Thread(target=run, args=(churn_release,)),
            threading.Thread(target=run, args=(churn_schedule,)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs[0]
        events = c.extender.trace.events()
        assert [e["kind"] for e in events].count("release") == 8
        divergences = trace_mod.replay(events, config=cfg)
        assert divergences == []


def test_trace_ring_bounded():
    t = trace_mod.DecisionTrace(capacity=4)
    for i in range(10):
        t.record("release", {"pod_key": f"ns/p{i}"}, None)
    evs = t.events()
    assert len(evs) == 4
    assert evs[-1]["seq"] == 10


def test_trace_sink_rotation_caps_file_size(tmp_path):
    """ISSUE 2 satellite: the JSONL sink rotates at max_sink_bytes
    (one <path>.1 generation) instead of growing without bound."""
    import os

    path = tmp_path / "trace.jsonl"
    t = trace_mod.DecisionTrace(capacity=16, path=str(path),
                                max_sink_bytes=2048)
    for i in range(200):
        t.record("release", {"pod_key": f"ns/pod-{i:04d}"}, None)
    t.close()
    assert os.path.exists(f"{path}.1")
    # both generations stay near the cap (one line of slack)
    assert os.path.getsize(path) <= 2048 + 200
    assert os.path.getsize(f"{path}.1") <= 2048 + 200
    stats = t.stats()
    assert stats["sink_rotations"] >= 1
    # the LIVE file still loads and carries the newest events in order
    evs = trace_mod.load(str(path))
    assert evs, "post-rotation sink must hold events"
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 200


def test_trace_sink_writes_stay_ordered_under_threads(tmp_path):
    """Sink writes moved OUT of the ring lock's critical section: lines
    must still land in seq order even with concurrent recorders."""
    path = tmp_path / "trace.jsonl"
    t = trace_mod.DecisionTrace(capacity=4096, path=str(path))
    errs = []

    def pound(start):
        try:
            for i in range(100):
                t.record("release", {"pod_key": f"ns/p{start}-{i}"}, None)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=pound, args=(n,)) for n in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    t.close()
    assert not errs
    seqs = [e["seq"] for e in trace_mod.load(str(path))]
    assert len(seqs) == 400
    assert seqs == sorted(seqs)


def test_trace_load_skips_torn_final_line(tmp_path):
    """A daemon that crashed mid-write leaves a torn last line; the
    loader (and therefore tpukube-obs timeline and replay) must keep
    the intact events."""
    path = tmp_path / "trace.jsonl"
    t = trace_mod.DecisionTrace(capacity=16, path=str(path))
    for i in range(3):
        t.record("release", {"pod_key": f"ns/p{i}"}, None)
    t.close()
    with open(path, "a") as f:
        f.write('{"seq": 4, "kind": "rel')  # torn mid-write
    evs = trace_mod.load(str(path))
    assert [e["seq"] for e in evs] == [1, 2, 3]
    assert trace_mod.replay(evs) == []

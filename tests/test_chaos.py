"""ISSUE 4 acceptance: chaos harness, degraded mode, informer backoff,
plugin registration retry, rebuild edge cases, and scenarios 8/9.
"""

from __future__ import annotations

import os
import random
import threading
import time

import pytest

from tpukube.apiserver import (
    AllocIntentWatcher,
    ApiServerError,
    FakeApiServer,
    transient_api_error,
)
from tpukube.chaos import (
    ChaosApiServer,
    ChaosSimCluster,
    ChaosSpec,
    FaultSchedule,
    converge,
    leaked_reservations,
    ledger_divergence,
)
from tpukube.core import codec, retry
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sched.extender import Extender
from tpukube.sim.harness import SimCluster


def small_cfg(**extra):
    env = {
        "TPUKUBE_SIM_MESH_DIMS": "4,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }
    env.update(extra)
    return load_config(env=env)


# -- fault schedule ----------------------------------------------------------

def test_fault_schedule_is_deterministic():
    spec = ChaosSpec(error_rate=0.3, timeout_rate=0.2, torn_rate=0.1)

    def draw_sequence(seed):
        s = FaultSchedule(seed, spec)
        return [s.draw_unary("op", mutating=True) for _ in range(50)]

    assert draw_sequence(7) == draw_sequence(7)
    assert draw_sequence(7) != draw_sequence(8)


def test_fault_schedule_budget_and_stop():
    s = FaultSchedule(1, ChaosSpec(error_rate=1.0), budget=2)
    kinds = [s.draw_unary("op", mutating=False) for _ in range(5)]
    assert kinds[:2] == ["error", "error"]
    assert kinds[2:] == [None, None, None]  # budget exhausted
    assert s.injected() == 2

    s2 = FaultSchedule(1, ChaosSpec(error_rate=1.0))
    assert s2.draw_unary("op", mutating=False) == "error"
    s2.stop()
    assert s2.draw_unary("op", mutating=False) is None
    s2.resume()
    assert s2.draw_unary("op", mutating=False) == "error"
    assert s2.report()["by_kind"] == {"error": 2}


def test_torn_only_applies_to_mutating_ops():
    s = FaultSchedule(1, ChaosSpec(torn_rate=1.0))
    assert s.draw_unary("get_pod", mutating=False) is None
    assert s.draw_unary("patch_pod_annotations", mutating=True) == "torn"


# -- chaos api proxy ---------------------------------------------------------

def _pod(name, annotations=None, node=None):
    pod = {
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}",
                     "annotations": dict(annotations or {})},
        "spec": {},
    }
    if node:
        pod["spec"]["nodeName"] = node
    return pod


def test_chaos_injects_503_and_timeout():
    api = ChaosApiServer(
        FakeApiServer(),
        FaultSchedule(1, ChaosSpec(error_rate=1.0), budget=1),
    )
    with pytest.raises(ApiServerError) as e:
        api.get_pod("default", "x")
    assert e.value.code == 503
    assert api.get_pod("default", "x") is None  # budget spent: clean

    api2 = ChaosApiServer(
        FakeApiServer(),
        FaultSchedule(1, ChaosSpec(timeout_rate=1.0), budget=1),
    )
    with pytest.raises(ApiServerError) as e:
        api2.get_pod("default", "x")
    assert e.value.code is None  # transport-shaped
    assert transient_api_error(e.value)


def test_chaos_torn_write_applies_then_raises():
    inner = FakeApiServer()
    inner.upsert_pod(_pod("p"))
    api = ChaosApiServer(
        inner, FaultSchedule(3, ChaosSpec(torn_rate=1.0), budget=1)
    )
    with pytest.raises(ApiServerError) as e:
        api.patch_pod_annotations("default", "p", {"k": "v"})
    assert "torn" in str(e.value)
    # ...but the write LANDED: the retrying caller must tolerate that
    assert inner.get_pod("default", "p")["metadata"]["annotations"][
        "k"] == "v"
    # the retry (budget spent) re-applies harmlessly
    api.patch_pod_annotations("default", "p", {"k": "v"})


def test_chaos_watch_gone_and_event_fates():
    inner = FakeApiServer()
    api = ChaosApiServer(
        inner, FaultSchedule(5, ChaosSpec(gone_rate=1.0), budget=1)
    )
    with pytest.raises(ApiServerError) as e:
        api.watch_pods(timeout_seconds=1)
    assert e.value.code == 410

    # drop: the first event vanishes; the stream then heals
    inner2 = FakeApiServer()
    api2 = ChaosApiServer(
        inner2, FaultSchedule(5, ChaosSpec(drop_event_rate=1.0), budget=1)
    )
    box: list = []
    gen = api2.watch_pods(timeout_seconds=5, handle_box=box)
    inner2.upsert_pod(_pod("a"))
    inner2.upsert_pod(_pod("b"))
    etype, obj = next(gen)
    assert obj["metadata"]["name"] == "b"  # "a" was dropped

    # dup: the first event arrives twice
    inner3 = FakeApiServer()
    api3 = ChaosApiServer(
        inner3, FaultSchedule(5, ChaosSpec(dup_event_rate=1.0), budget=1)
    )
    gen3 = api3.watch_pods(timeout_seconds=5, handle_box=[])
    inner3.upsert_pod(_pod("a"))
    first = next(gen3)
    second = next(gen3)
    assert first[1]["metadata"]["name"] == "a"
    assert second[1]["metadata"]["name"] == "a"


# -- informer reconnect backoff (satellite: 410 resync) ----------------------

class _StubServer:
    def __init__(self) -> None:
        from tpukube.plugin.server import AllocIntentCache

        self.intents = AllocIntentCache()


def test_watch_loop_backoff_grows_on_consecutive_failures():
    """A persistently-failing watch (410 storm, down apiserver) must
    back off with capped exponential growth, not a fixed cadence."""

    class Always410:
        def list_pods_with_rv(self, node_name=None):
            return [], "0"

        def watch_pods(self, node_name=None, handle_box=None,
                       resource_version=None):
            raise ApiServerError("resourceVersion too old", code=410)

    loop = AllocIntentWatcher(Always410(), "n0", _StubServer(),
                              poll_seconds=1.0, use_watch=True)
    loop._reconnect_backoff = retry.Backoff(base=1.0, cap=16.0, jitter=0.0)
    delays: list[float] = []

    real_is_set = loop._stop.is_set

    def fake_wait(delay):
        delays.append(delay)
        if len(delays) >= 5:
            loop._stop.set()
        return real_is_set()

    loop._stop.wait = fake_wait  # run _run inline, deterministically
    loop._run()
    assert delays == [1.0, 2.0, 4.0, 8.0, 16.0]
    assert loop.watch_status()["reconnect_failures"] == 5


def test_watch_loop_410_resync_covers_the_gap():
    """Regression for the list->watch resync gap: a 410 Gone on
    subscribe must lead to a fresh list whose content is applied —
    intents created during the outage are not lost."""
    inner = FakeApiServer()
    from tpukube.core.types import AllocResult, TopologyCoord

    payload = codec.encode_alloc(AllocResult(
        pod_key="default/p0", node_name="n0", device_ids=["tpu-0"],
        coords=[TopologyCoord(0, 0, 0)], env={}, priority=0, uid="u0",
    ))
    inner.upsert_pod(_pod("p0", annotations={codec.ANNO_ALLOC: payload},
                          node="n0"))
    api = ChaosApiServer(
        inner, FaultSchedule(5, ChaosSpec(gone_rate=1.0), budget=1)
    )
    server = _StubServer()
    loop = AllocIntentWatcher(api, "n0", server, poll_seconds=0.01,
                              use_watch=True)
    loop._reconnect_backoff = retry.Backoff(base=0.01, cap=0.05,
                                            jitter=0.0)
    loop.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            # the post-410 reconnect landed and the outage-era intent
            # was resynced from the fresh list
            if (server.intents.snapshot().get("default/p0") == ["tpu-0"]
                    and loop.stream_connected()):
                break
            time.sleep(0.01)
        assert server.intents.snapshot().get("default/p0") == ["tpu-0"]
        assert loop.stream_connected()
        # a delivered watch event is the liveness proof that resets the
        # reconnect backoff (an idle dial alone must not)
        payload2 = codec.encode_alloc(AllocResult(
            pod_key="default/p1", node_name="n0", device_ids=["tpu-1"],
            coords=[TopologyCoord(1, 0, 0)], env={}, priority=0, uid="u1",
        ))
        inner.upsert_pod(_pod("p1", annotations={codec.ANNO_ALLOC: payload2},
                              node="n0"))
        while time.monotonic() < deadline:
            if (server.intents.snapshot().get("default/p1") == ["tpu-1"]
                    and loop._reconnect_backoff.failures == 0):
                break
            time.sleep(0.01)
        assert server.intents.snapshot().get("default/p1") == ["tpu-1"]
        assert loop._reconnect_backoff.failures == 0  # healthy again
    finally:
        loop.stop()


# -- plugin registration retry (satellite) -----------------------------------

class _FakePluginServer:
    """Just enough DevicePluginServer surface for the session watcher."""

    class _Device:
        host = "n0"

    def __init__(self, tmp_path, fail_times: int) -> None:
        self.config = load_config(env={
            "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        })
        # both sockets "exist" as plain files
        for name in ("kubelet.sock", "tpukube.sock"):
            with open(os.path.join(str(tmp_path), name), "w") as f:
                f.write("")
        self.socket_path = os.path.join(str(tmp_path), "tpukube.sock")
        self._device = self._Device()
        self._fail_times = fail_times
        self.register_calls = 0
        self.restarts = 0

    def restart(self):
        self.restarts += 1

    def register_with_kubelet(self):
        self.register_calls += 1
        if self.register_calls <= self._fail_times:
            raise ConnectionError("kubelet not serving yet")


def test_registration_retries_with_backoff_then_emits(tmp_path):
    from tpukube.obs.events import EventJournal
    from tpukube.plugin.server import KubeletSessionWatcher

    server = _FakePluginServer(tmp_path, fail_times=2)
    sleeps: list[float] = []
    retrier = retry.Retrier(
        retry.RetryPolicy(max_attempts=5, base_delay=0.05, max_delay=1.0,
                          jitter=0.5, deadline=0),
        name="kubelet-register", sleep=sleeps.append,
        rng=random.Random(3),
    )
    watcher = KubeletSessionWatcher(server, poll_seconds=999,
                                    retrier=retrier)
    watcher.events = EventJournal(capacity=16)
    watcher.mark_unregistered()  # the initial-registration-failed path

    assert watcher.check_once() is True
    assert server.register_calls == 3  # 2 failures + the success
    assert len(sleeps) == 2
    # jittered exponential: within (1-jitter)*ideal .. ideal
    assert 0.025 <= sleeps[0] <= 0.05
    assert 0.05 <= sleeps[1] <= 0.1
    assert watcher.reregistrations == 1
    evs = watcher.events.events(reason="KubeletReregistered")
    assert len(evs) == 1
    assert "recovered" in evs[0]["message"]
    assert "attempt 3" in evs[0]["message"]
    assert retrier.stats.retries == 2


def test_registration_gives_up_after_max_attempts(tmp_path):
    from tpukube.plugin.server import KubeletSessionWatcher

    server = _FakePluginServer(tmp_path, fail_times=99)
    retrier = retry.Retrier(
        retry.RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0,
                          deadline=0),
        name="kubelet-register", sleep=lambda s: None,
    )
    watcher = KubeletSessionWatcher(server, poll_seconds=999,
                                    retrier=retrier)
    watcher.mark_unregistered()
    with pytest.raises(ConnectionError):
        watcher.check_once()
    assert server.register_calls == 3  # max attempts, not a tight loop
    assert watcher.reregistrations == 0
    # the flag survives, so the NEXT poll retries again
    assert watcher._needs_register is True


def test_default_watcher_retrier_comes_from_config(tmp_path):
    from tpukube.plugin.server import KubeletSessionWatcher

    server = _FakePluginServer(tmp_path, fail_times=0)
    watcher = KubeletSessionWatcher(server, poll_seconds=999)
    assert watcher.retrier.policy.max_attempts == \
        server.config.retry_max_attempts


# -- degraded mode -----------------------------------------------------------

def _filter_body(cluster, pod):
    return {"Pod": pod, "Nodes": {"Items": cluster.node_objects()}}


def test_degraded_mode_fails_filter_and_bind_safe():
    cfg = small_cfg()
    with SimCluster(cfg) as c:
        # healthy: filter works
        pod = c.make_pod("p0", tpu=1)
        ext = c.extender
        out = ext.handle("filter", _filter_body(c, pod))
        assert out["NodeNames"] and not out["Error"]

        reason_box = ["apiserver circuit open"]
        ext.degraded_gate = lambda: reason_box[0]
        trace_len = len(ext.trace.events())
        pod2 = c.make_pod("p1", tpu=1, priority=10,
                          group=PodGroup("g", min_member=2))
        out = ext.handle("filter", _filter_body(c, pod2))
        assert "degraded mode" in out["Error"]
        assert out["NodeNames"] == []
        # fail SAFE: no reservation was created, nothing recorded
        assert ext.gang.reservation("default", "g") is None
        assert len(ext.trace.events()) == trace_len
        bout = ext.handle("bind", {
            "PodName": "p1", "PodNamespace": "default", "PodUID": "u",
            "Node": "host-0-0-0",
        })
        assert "degraded mode" in bout["Error"]
        assert ext.events.counts_by_reason().get("DegradedMode", 0) >= 2

        # circuit closes -> normal service resumes, no restart needed
        reason_box[0] = None
        out = ext.handle("filter", _filter_body(c, pod))
        assert out["NodeNames"] and not out["Error"]


def test_degraded_gauge_and_retry_series_render():
    from tpukube.metrics import render_extender_metrics

    cfg = small_cfg()
    ext = Extender(cfg)
    text = render_extender_metrics(ext)
    assert "tpukube_degraded_mode" not in text  # nothing wired: legacy
    assert "tpukube_retry_attempts_total" not in text

    ext.api_retrier = retry.Retrier(retry.RetryPolicy(), name="apiserver")
    ext.api_circuit = retry.CircuitBreaker(
        failure_threshold=3, reset_seconds=5, name="apiserver")
    ext.degraded_gate = lambda: "apiserver circuit open"
    text = render_extender_metrics(ext)
    assert "tpukube_degraded_mode 1\n" in text
    assert 'tpukube_retry_attempts_total{op="apiserver"} 0' in text
    assert 'tpukube_circuit_state{circuit="apiserver"} 0' in text
    assert 'tpukube_circuit_opens_total{circuit="apiserver"} 0' in text


def test_plugin_registry_renders_registration_retrier(tmp_path):
    from tpukube.metrics import render_plugin_metrics
    from tpukube.plugin.server import KubeletSessionWatcher

    server = _FakePluginServer(tmp_path, fail_times=0)
    watcher = KubeletSessionWatcher(server, poll_seconds=999)

    class _SrvForMetrics:
        allocation_count = 0
        divergences = 0
        resource_name = "qiniu.com/tpu"
        intents = server  # unused paths below avoid it

    # the real render needs a full DevicePluginServer; assert through
    # the shared helper instead
    from tpukube.metrics import _add_retry_metrics
    from tpukube.obs.registry import Registry

    reg = Registry()
    _add_retry_metrics(reg, retriers=[watcher.retrier])
    text = reg.render()
    assert 'tpukube_retry_attempts_total{op="kubelet-register"} 0' in text


# -- RestApiServer through the unified layer ---------------------------------

def _rest_server(**kw):
    from tpukube.apiserver import RestApiServer

    return RestApiServer(base_url="http://127.0.0.1:1", token="t", **kw)


def test_rest_requests_retry_transient_errors(monkeypatch):
    api = _rest_server(retrier=retry.Retrier(
        retry.RetryPolicy(max_attempts=4, base_delay=0.001, jitter=0.0,
                          deadline=0),
        name="apiserver", retryable=transient_api_error,
        sleep=lambda s: None,
    ))
    calls = []

    def flaky(method, path, body=None, content_type=""):
        calls.append(method)
        if len(calls) < 3:
            raise ApiServerError("injected 503", code=503)
        return {"metadata": {"annotations": {"a": "1"}}}

    monkeypatch.setattr(api, "_request_once", flaky)
    assert api.get_node_annotations("n") == {"a": "1"}
    assert len(calls) == 3


def test_rest_requests_do_not_retry_logical_answers(monkeypatch):
    api = _rest_server(retrier=retry.Retrier(
        retry.RetryPolicy(max_attempts=4, base_delay=0.001, jitter=0.0,
                          deadline=0),
        name="apiserver", retryable=transient_api_error,
        sleep=lambda s: None,
    ))
    calls = []

    def not_found(method, path, body=None, content_type=""):
        calls.append(method)
        raise ApiServerError("nope", code=404)

    monkeypatch.setattr(api, "_request_once", not_found)
    assert api.get_pod("default", "x") is None  # 404 -> None, 1 call
    assert len(calls) == 1


def test_rest_circuit_opens_and_fails_fast(monkeypatch):
    circuit = retry.CircuitBreaker(failure_threshold=2, reset_seconds=60,
                                   name="apiserver")
    api = _rest_server(circuit=circuit)
    calls = []

    def down(method, path, body=None, content_type=""):
        calls.append(method)
        raise ApiServerError("conn refused")

    monkeypatch.setattr(api, "_request_once", down)
    for _ in range(2):
        with pytest.raises(ApiServerError):
            api.get_node_annotations("n")
    assert circuit.state() == retry.OPEN
    with pytest.raises(ApiServerError) as e:
        api.get_node_annotations("n")
    assert "circuit" in str(e.value)
    assert len(calls) == 2  # the fast-fail never dialed


# -- eviction GET-confirms through the retrier -------------------------------

def test_eviction_confirm_retries_through_policy():
    cfg = small_cfg()
    schedule_ = FaultSchedule(11, ChaosSpec(), budget=0)  # quiet chaos
    with ChaosSimCluster(cfg, schedule_) as c:
        assert c._evictions.retrier is c.confirm_retrier
        c.schedule(c.make_pod("victim", tpu=1))
        c.extender.handle("release", {"pod_key": "default/victim"})
        c.extender.pending_evictions.append("default/victim")
        # storm ONLY the confirm path: every get_pod 503s a few times
        schedule_.resume(ChaosSpec(error_rate=0.5))
        schedule_.budget = None
        done: list[str] = []
        for _ in range(20):
            done += c.drain_evictions()
            if done:
                break
        assert done == ["default/victim"]
        assert c.confirm_retrier.stats.attempts >= 1


# -- rebuild_from_pods edge cases (satellite) --------------------------------

def _fresh_from(cluster, annotations_list):
    fresh = Extender(cluster.config)
    for obj in cluster.node_objects():
        fresh.state.upsert_node(
            obj["metadata"]["name"], obj["metadata"]["annotations"]
        )
    return fresh, fresh.rebuild_from_pods(annotations_list)


def test_rebuild_malformed_gang_annotation_on_one_member():
    """One member's undecodable pod-group annotation must not abort the
    rebuild, and must not leave the OTHER members individually
    evictable: they either restore under one reservation or die
    together (all-or-nothing preserved either way)."""
    cfg = small_cfg()
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=10,
                                  group=group))
        annos = {
            k: dict(p["metadata"]["annotations"])
            for k, p in c.pods.items()
        }
        annos["default/g-0"][codec.ANNO_POD_GROUP_MIN_MEMBER] = "banana"
        fresh, restored = _fresh_from(c, list(annos.values()))
        assert restored == 4  # the LEDGER always restores fully
        intact = {f"default/g-{i}" for i in range(1, 4)}
        res = fresh.gang.reservation("default", "g")
        if res is not None:
            # all intact members live inside the one reservation
            assert intact <= set(res.assigned)
        else:
            # ...or the whole remnant was rolled back together
            assert all(fresh.state.allocation(k) is None for k in intact)
            assert intact <= set(fresh.pending_evictions)


def test_rebuild_partial_gang_missing_member_pod():
    """A member pod missing at restart (annotation never listed): the
    survivors restore as ONE uncommitted reservation whose re-derived
    slice still covers a full-size box — the late member can complete
    the gang instead of the survivors becoming strays."""
    cfg = small_cfg()
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=10,
                                  group=group))
        annos = [
            dict(p["metadata"]["annotations"])
            for k, p in c.pods.items() if k != "default/g-3"
        ]
        fresh, restored = _fresh_from(c, annos)
        assert restored == 3
        res = fresh.gang.reservation("default", "g")
        assert res is not None and not res.committed
        assert len(res.assigned) == 3
        # the reservation holds a full-size pool (4 chips) so the gang
        # can still complete
        assert res.total_chips() == 4


def test_rebuild_mid_commit_preserves_all_or_nothing_death():
    """Restart mid-gang-commit (2 of 4 members bound; the others'
    reservations existed only in the dead extender's memory). After
    rebuild + completion, a preemption that needs the gang's chips
    must dissolve the WHOLE gang — no member may die alone."""
    cfg = small_cfg()
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(2):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=10,
                                  group=group))
        c.crash_extender()
        restored = c.restart_extender()
        assert restored == 2
        res = c.extender.gang.reservation("default", "g")
        assert res is not None and not res.committed
        assert len(res.assigned) == 2

        # the remaining members complete the gang after the restart
        for i in range(2, 4):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=10,
                                  group=group))
        res = c.extender.gang.reservation("default", "g")
        assert res is not None and res.committed

        # a mesh-wide prio-100 gang preempts: the restored gang dies
        # WHOLE — every member released and queued, none survives
        vip = PodGroup("vip", min_member=8)
        for i in range(8):
            c.schedule(c.make_pod(f"vip-{i}", tpu=1, priority=100,
                                  group=vip))
        assert c.extender.gang.reservation("default", "g") is None
        for i in range(4):
            assert c.extender.state.allocation(f"default/g-{i}") is None
            assert f"default/g-{i}" not in c.pods  # evicted, not stray
        assert ledger_divergence(c) == []


# -- scenarios 8 / 9 ---------------------------------------------------------

def test_scenario8_apiserver_chaos_acceptance():
    from tpukube.sim import scenarios

    result = scenarios.run(8)
    assert result["scenario"] == 8
    assert result["leaked_reservations"] == 0
    assert result["ledger_divergence"] == 0
    assert result["evictions_pending"] == 0
    assert result["gang_committed"] is True
    assert result["faults"]["injected"] > 0
    assert result["circuit"]["opens"] >= 1
    assert result["degraded_refusals"] >= 1
    assert result["blackout_refused"] is True
    assert result["retry"]["bind_retries"] >= 1


def test_scenario8_is_deterministic_for_a_seed():
    from tpukube.sim import scenarios

    a = scenarios.run(8)
    b = scenarios.run(8)
    assert a["faults"] == b["faults"]
    assert a["preemptions"] == b["preemptions"]


def test_scenario9_crash_recovery_acceptance():
    from tpukube.sim import scenarios

    result = scenarios.run(9)
    assert result["scenario"] == 9
    assert result["restored"] == 4
    assert result["partial_gang_restored"] is True
    assert result["gang_committed"] is True
    assert result["leaked_reservations"] == 0
    assert result["ledger_divergence"] == 0
    assert result["agent_restart_allocate_ok"] is True
    assert result["recovery_s"] < 30.0


def test_chaos_off_keeps_sim_cluster_behavior_identical():
    """chaos_seed unset + circuits disabled = byte-identical legacy
    behavior: a quiet FaultSchedule injects nothing and the plain
    SimCluster path runs no chaos code at all."""
    cfg = small_cfg()
    assert cfg.chaos_seed == 0
    quiet = FaultSchedule(0, ChaosSpec())
    with ChaosSimCluster(cfg, quiet) as c:
        c.schedule(c.make_pod("p", tpu=1))
        assert quiet.injected() == 0
        assert c.circuit.opens == 0
        assert ledger_divergence(c) == []
        assert leaked_reservations(c) == []
        assert converge(c) >= 1

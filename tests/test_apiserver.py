"""Apiserver channel: annotation syncer, alloc-intent steering, and the
extender<->kubelet device-id reconciliation loop (SURVEY.md §4.1/§4.3)."""

import json
import threading

import pytest

from tpukube import apiserver as apisrv
from tpukube.core import codec
from tpukube.core.config import load_config
from tpukube.sim import SimCluster

HBM = 16 << 30


def _node_cfg(tmp_path, dims="4,4,1", block="2,2,1", extra=None):
    env = {
        "TPUKUBE_DEVICE_PLUGIN_DIR": str(tmp_path),
        "TPUKUBE_SIM_MESH_DIMS": dims,
        "TPUKUBE_SIM_HOST_BLOCK": block,
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    }
    env.update(extra or {})
    return load_config(env=env)


# -- NodeAnnotationSyncer ----------------------------------------------------

def test_extender_learns_topology_only_through_syncer(tmp_path):
    """E2E for the apiserver writer the round-1 plugin left to 'an external
    writer': plugin emits its annotation file, the syncer PATCHes the Node,
    and the extender schedules from what the apiserver now carries —
    no other topology channel exists in this test."""
    from tpukube.device import TpuDeviceManager
    from tpukube.sched.extender import Extender

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    api = apisrv.FakeApiServer()
    anno_file = tmp_path / "annotation.json"

    # the node agent side: write the annotation file (what main_plugin's
    # --annotation-out does), then sync it
    with TpuDeviceManager(cfg, host="host-0-0-0") as device:
        anno = codec.annotate_node(device.node_info(), device.mesh)
    anno_file.write_text(json.dumps(anno) + "\n")
    syncer = apisrv.NodeAnnotationSyncer(
        api, "host-0-0-0", str(anno_file), poll_seconds=999
    )
    assert syncer.check_once() is True
    assert syncer.check_once() is False  # unchanged content: no re-patch
    assert codec.ANNO_NODE_TOPOLOGY in api.get_node_annotations("host-0-0-0")

    # the scheduler side sees ONLY the apiserver's node objects
    ext = Extender(cfg)
    pod_obj = {
        "metadata": {
            "name": "p0", "namespace": "default", "uid": "u0",
            "annotations": {},
        },
        "spec": {"containers": [{
            "name": "main",
            "resources": {"limits": {cfg.resource_tpu: "1"}},
        }]},
    }
    result = ext.handle(
        "filter", {"Pod": pod_obj, "Nodes": {"Items": api.node_objects()}}
    )
    assert [n["metadata"]["name"] for n in result["Nodes"]["Items"]] == [
        "host-0-0-0"
    ]

    # a health re-annotation flows the same way: new content -> new patch
    anno2 = dict(anno)
    payload = json.loads(anno2[codec.ANNO_NODE_TOPOLOGY])
    payload["chips"][0]["health"] = "Unhealthy"
    anno2[codec.ANNO_NODE_TOPOLOGY] = json.dumps(payload)
    anno_file.write_text(json.dumps(anno2) + "\n")
    assert syncer.check_once() is True
    assert syncer.syncs == 2


def test_syncer_tolerates_missing_and_garbage_file(tmp_path):
    api = apisrv.FakeApiServer()
    syncer = apisrv.NodeAnnotationSyncer(
        api, "n0", str(tmp_path / "nope.json"), poll_seconds=999
    )
    assert syncer.check_once() is False  # agent not up yet
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    syncer = apisrv.NodeAnnotationSyncer(api, "n0", str(bad), poll_seconds=999)
    assert syncer.check_once() is False
    assert api.get_node_annotations("n0") == {}


# -- RestApiServer -----------------------------------------------------------

def test_rest_apiserver_speaks_merge_patch():
    """The no-client-library REST writer sends bearer-authed JSON
    merge-patches and field-selector GETs (verified against a local HTTP
    stand-in; no cluster exists in this environment)."""
    import http.server

    seen = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def _reply(self, obj):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_PATCH(self):
            n = int(self.headers["Content-Length"])
            seen.append((
                "PATCH", self.path,
                self.headers.get("Authorization"),
                self.headers.get("Content-Type"),
                json.loads(self.rfile.read(n)),
            ))
            self._reply({})

        def do_GET(self):
            seen.append(("GET", self.path, None, None, None))
            self._reply({"items": [
                {"metadata": {"name": "p0", "namespace": "default"}}
            ]})

        def log_message(self, *a):  # quiet
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="sekrit",
        )
        api.patch_node_annotations("n1", {"a": "b"})
        api.patch_pod_annotations("default", "p0", {"x": None})
        pods = api.list_pods("n1")
        assert pods[0]["metadata"]["name"] == "p0"
    finally:
        httpd.shutdown()

    method, path, auth, ctype, body = seen[0]
    assert (method, path) == ("PATCH", "/api/v1/nodes/n1")
    assert auth == "Bearer sekrit"
    assert ctype == "application/merge-patch+json"
    assert body == {"metadata": {"annotations": {"a": "b"}}}
    method, path, _, _, body = seen[1]
    assert (method, path) == ("PATCH", "/api/v1/namespaces/default/pods/p0")
    assert body == {"metadata": {"annotations": {"x": None}}}  # null deletes
    assert seen[2][1] == (
        "/api/v1/pods?limit=500&fieldSelector=spec.nodeName%3Dn1"
    )


def test_rest_list_pods_paginates():
    """Large clusters: list_pods follows the apiserver's limit/continue
    protocol and returns the concatenation of all pages."""
    import http.server

    pages = {
        "": {"items": [{"metadata": {"name": "p0"}}],
             "metadata": {"continue": "tok 1"}},
        "tok 1": {"items": [{"metadata": {"name": "p1"}}],
                  "metadata": {"continue": "tok2"}},
        "tok2": {"items": [{"metadata": {"name": "p2"}}],
                 "metadata": {"resourceVersion": "9001"}},
    }
    paths = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            paths.append(self.path)
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            cont = q.get("continue", [""])[0]
            body = json.dumps(pages[cont]).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="t",
        )
        pods = api.list_pods()
        pods_rv, rv = api.list_pods_with_rv()
    finally:
        httpd.shutdown()
    assert [p["metadata"]["name"] for p in pods] == ["p0", "p1", "p2"]
    assert [p["metadata"]["name"] for p in pods_rv] == ["p0", "p1", "p2"]
    assert rv == "9001"  # the informer's watch starting point
    assert len(paths) == 6
    assert "continue=tok%201" in paths[1]  # token is URL-quoted


# -- alloc intents: steering -------------------------------------------------

def test_intent_steers_preferred_allocation(tmp_path):
    """The extender's planned ids win GetPreferredAllocation over the local
    adjacency heuristic: a kubelet that honors preference converges on the
    planned chips without ever knowing the plan's origin."""
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer, FakeKubelet

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    with TpuDeviceManager(cfg, host="host-0-0-0") as device, \
            DevicePluginServer(cfg, device) as server, \
            FakeKubelet(str(tmp_path)) as kubelet:
        server.register_with_kubelet()
        devs = sorted(kubelet.wait_for_devices(server.resource_name, 4))
        assert devs == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]

        # without an intent the heuristic picks its own adjacency-greedy
        # pair; with the plan in place the answer is exactly the plan
        baseline = kubelet.preferred(server.resource_name, devs, 2)
        server.intents.put("default/p0", ["tpu-1", "tpu-3"])
        steered = kubelet.preferred(server.resource_name, devs, 2)
        assert sorted(steered) == ["tpu-1", "tpu-3"]
        assert sorted(steered) != sorted(baseline) or baseline == steered

        # a plan that the available pool cannot satisfy is ignored
        server.intents.sync({"default/p1": ["tpu-0", "tpu-9"]})
        fallback = kubelet.preferred(server.resource_name, devs, 2)
        assert sorted(fallback) == sorted(baseline)


def test_intent_watcher_feeds_pod_allocs(tmp_path):
    """AllocIntentWatcher: pods bound to this node with alloc annotations
    become intents; pods that vanish drop out on the next poll."""
    from tpukube.core.types import AllocResult, TopologyCoord
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    api = apisrv.FakeApiServer()
    alloc = AllocResult(
        pod_key="default/w0", node_name="host-0-0-0",
        device_ids=["tpu-0", "tpu-2"],
        coords=[TopologyCoord(0, 0, 0), TopologyCoord(0, 1, 0)],
    )
    api.upsert_pod({
        "metadata": {"name": "w0", "namespace": "default", "annotations": {
            codec.ANNO_ALLOC: codec.encode_alloc(alloc),
        }},
        "spec": {"nodeName": "host-0-0-0"},
    })
    api.upsert_pod({  # other node: not ours
        "metadata": {"name": "w1", "namespace": "default", "annotations": {}},
        "spec": {"nodeName": "host-1-0-0"},
    })
    with TpuDeviceManager(cfg, host="host-0-0-0") as device:
        server = DevicePluginServer(cfg, device)
        watch = apisrv.AllocIntentWatcher(
            api, "host-0-0-0", server, poll_seconds=999
        )
        assert watch.check_once() is True
        assert server.intents.snapshot() == {
            "default/w0": ["tpu-0", "tpu-2"]
        }
        assert watch.check_once() is False  # no change
        api.delete_pod("default", "w0")
        assert watch.check_once() is True
        assert server.intents.snapshot() == {}


# -- the divergence loop -----------------------------------------------------

def test_kubelet_divergence_reconciles_extender_ledger(tmp_path):
    """The full extender<->kubelet device-id loop, divergent case: the
    extender plans chips at bind; the kubelet allocates DIFFERENT ids; the
    node agent reports the actual ids through the pod annotation; the
    reconcile loop folds reality into the ledger, so follow-up scheduling
    and release account the chips the container really holds."""
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer, FakeKubelet

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as cluster:
        pod = cluster.make_pod("train-0", tpu=2)
        node, alloc = cluster.schedule(pod)
        planned = sorted(alloc.device_ids)

        api = apisrv.FakeApiServer()
        api.upsert_pod(pod)  # the scheduler's bind annotated + noded it

        # node agent stack for the bound node, intents fed from the pod
        ncfg = _node_cfg(
            tmp_path,
            extra={"TPUKUBE_SIM_HOST_ORIGIN": ",".join(
                str(v) for v in min(
                    c.coord for c in cluster.nodes[node].chips
                )
            )},
        )
        with TpuDeviceManager(ncfg, host=node) as device, \
                DevicePluginServer(ncfg, device) as server, \
                FakeKubelet(str(tmp_path)) as kubelet:
            server.register_with_kubelet()
            server.set_alloc_reporter(apisrv.alloc_divergence_reporter(api))
            kubelet.wait_for_devices(server.resource_name, 4)
            watch = apisrv.AllocIntentWatcher(
                api, node, server, poll_seconds=999
            )
            assert watch.check_once() is True

            # the kubelet ignores preference and allocates the OTHER chips
            all_ids = ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
            actual = [d for d in all_ids if d not in planned][:2]
            assert sorted(actual) != planned
            kubelet.allocate(server.resource_name, actual)

        # report landed on the pod
        pod_key = f"default/train-0"
        [stored] = [
            p for p in api.list_pods(node)
            if p["metadata"]["name"] == "train-0"
        ]
        assert apisrv.ANNO_ALLOC_ACTUAL in stored["metadata"]["annotations"]

        # extender folds it in
        loop = apisrv.AllocReconcileLoop(
            cluster.extender, api, poll_seconds=999
        )
        assert loop.check_once() is True
        ledger = cluster.extender.state.allocation(pod_key)
        assert sorted(ledger.device_ids) == sorted(actual)
        # the pod's alloc annotation now tells the truth; report cleared
        annos = stored["metadata"]["annotations"]
        assert apisrv.ANNO_ALLOC_ACTUAL not in annos
        assert sorted(
            codec.decode_alloc(annos[codec.ANNO_ALLOC]).device_ids
        ) == sorted(actual)
        assert loop.check_once() is False  # idempotent

        # accounting follows reality: the planned chips are free again,
        # the actual chips are held — a 2-chip pod fits on this node and
        # must land on the planned (now-free) ids
        pod2 = cluster.make_pod("train-1", tpu=2)
        node2, alloc2 = cluster.schedule(pod2)
        if node2 == node:
            assert sorted(alloc2.device_ids) == planned


def test_consumed_intent_never_reenters_and_ambiguity_refused():
    """Attribution safety: a consumed intent must not re-enter from the
    watcher's lifetime-annotation polls, and a divergent Allocate matching
    several same-size intents is never guessed."""
    from tpukube.plugin.server import AllocIntentCache

    c = AllocIntentCache()
    assert c.sync({"default/a": ["tpu-0", "tpu-1"]}) is True
    key, planned, diverged = c.consume(["tpu-1", "tpu-0"])
    assert (key, diverged) == ("default/a", False)
    # the pod keeps its alloc annotation for life; re-delivery is a no-op
    assert c.sync({"default/a": ["tpu-0", "tpu-1"]}) is False
    assert c.snapshot() == {}
    # pod deleted -> satisfied record forgotten -> a NEW pod with the same
    # key becomes a fresh intent
    assert c.sync({}) is False
    assert c.sync({"default/a": ["tpu-2", "tpu-3"]}) is True

    c2 = AllocIntentCache()
    c2.sync({"default/a": ["tpu-0", "tpu-1"], "default/b": ["tpu-2", "tpu-3"]})
    key, planned, diverged = c2.consume(["tpu-0", "tpu-3"])
    assert (key, planned, diverged) == (None, None, False)
    assert len(c2.snapshot()) == 2  # nothing consumed on ambiguity


def test_reconcile_refuses_chips_held_by_another_pod():
    """A stale/misattributed alloc-actual report naming another pod's chips
    must not touch the ledger (defense against attribution guesses)."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as cluster:
        _, a0 = cluster.schedule(cluster.make_pod("p0", tpu=1))
        _, a1 = cluster.schedule(cluster.make_pod("p1", tpu=1))
        ext = cluster.extender
        out = ext.handle("reconcile", {
            "pod_key": "default/p0", "devices": list(a1.device_ids),
        })
        assert out == {"changed": False}
        ledger = ext.state.allocation("default/p0")
        assert sorted(ledger.device_ids) == sorted(a0.device_ids)


def test_pending_preemption_box_clashes_for_other_gangs():
    """A reservation awaiting deferred evictions still excludes its chips
    from every OTHER gang's exact-reserve path — only the declared victim
    gangs are exempt from the clash check."""
    from tpukube.core.types import (
        RESOURCE_TPU, ContainerInfo, PodGroup, PodInfo, ResourceList,
    )
    from tpukube.sched.gang import GangError

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as cluster:
        for i in range(16):
            cluster.schedule(cluster.make_pod(f"s-{i}", tpu=1, priority=5))
        ext = cluster.extender
        vip = PodInfo(
            name="vip-0", namespace="default", priority=100,
            group=PodGroup("vip", min_member=4),
            containers=[ContainerInfo("main", ResourceList({RESOURCE_TPU: 1}))],
        )
        ext.filter(vip, cluster.node_objects())
        res = ext.gang.reservation("default", "vip")
        assert res is not None and res.pending_victims
        coords = sorted(res.coords)

        rival = PodInfo(
            name="r-0", namespace="default", priority=100,
            group=PodGroup("rival", min_member=4),
            containers=[ContainerInfo("main", ResourceList({RESOURCE_TPU: 1}))],
        )
        with pytest.raises(GangError, match="re-occupied"):
            ext.gang.reserve_exact(rival, 1, coords, slice_id=res.slice_id)


def test_reconcile_updates_gang_assignment(tmp_path):
    """A gang member whose kubelet allocation diverged must have its gang
    bookkeeping follow: releasing the member afterwards frees the ACTUAL
    coords, not the planned ones."""
    from tpukube.core.types import PodGroup

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as cluster:
        group = PodGroup("g", min_member=2)
        allocs = {}
        for i in range(2):
            _, a = cluster.schedule(
                cluster.make_pod(f"g-{i}", tpu=1, group=group)
            )
            allocs[f"default/g-{i}"] = a
        ext = cluster.extender
        res = ext.gang.reservation("default", "g")
        assert res is not None and res.committed

        # swap one member onto its node's other free chip (if any): find a
        # node-local id not used by anyone
        victim_key = "default/g-0"
        victim = allocs[victim_key]
        view = ext.state.node(victim.node_name)
        free = [
            c for c in view.info.chips
            if f"tpu-{c.index}" not in view.used_ids
        ]
        if not free:
            pytest.skip("gang packed its node full; no divergent chip")
        actual_id = f"tpu-{free[0].index}"
        out = ext.handle("reconcile", {
            "pod_key": victim_key, "devices": [actual_id],
        })
        assert out == {"changed": True}
        sid, coords = res.assigned[victim_key]
        assert coords == [free[0].coord]
        # the reservation's chip pool moved with the member: the abandoned
        # planned coord is ledger-free and must NOT linger as
        # reserved-but-unassigned (capacity leak), and assignable() must
        # not re-open for overflow replicas
        assert victim.coords[0] not in res.slice_coords[sid]
        assert free[0].coord in res.slice_coords[sid]
        assert victim.coords[0] not in ext.gang.reserved_coords(sid)
        assert not ext.gang.assignable(res, 1)
        # release frees the actual chip, not the planned one
        ext.release(victim_key)
        view2 = ext.state.node(victim.node_name)
        assert actual_id not in view2.used_ids


# -- eviction executor -------------------------------------------------------

def _vip_gang_pod(name: str, min_member: int = 4):
    from tpukube.core.types import (
        RESOURCE_TPU, ContainerInfo, PodGroup, PodInfo, ResourceList,
    )

    return PodInfo(
        name=name, namespace="default", priority=100,
        group=PodGroup("vip", min_member=min_member),
        containers=[ContainerInfo("main", ResourceList({RESOURCE_TPU: 1}))],
    )


def test_eviction_executor_e2e_preemption():
    """Decision -> effector, end to end on the apiserver channel: a
    priority gang's first bind executes its preemption plan, victims land
    on pending_evictions, and EvictionExecutor deletes them THROUGH the
    (fake) apiserver — the real-cluster path; the sim's drain_evictions
    is a thin wrapper over the same executor."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        for i in range(16):
            pod = c.make_pod(f"s-{i}", tpu=1, priority=5)
            c.schedule(pod)
            api.upsert_pod(pod)
        ext = c.extender
        from tpukube.sched.extender import ExtenderError

        feasible, _ = ext.filter(_vip_gang_pod("vip-0"), c.node_objects())
        target = feasible[0]["metadata"]["name"]
        # first bind EXECUTES the plan but does not proceed: the victims'
        # containers still hold the chips until their objects are gone
        with pytest.raises(ExtenderError, match="finish terminating"):
            ext.bind("vip-0", "default", "", target)
        victims = list(ext.pending_evictions)
        assert len(victims) == 4

        execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
        assert execu.depth() == 4
        assert execu.check_once() is True
        assert not ext.pending_evictions
        assert execu.evicted == 4
        remaining = {
            f"{p['metadata']['namespace']}/{p['metadata']['name']}"
            for p in api.list_pods()
        }
        assert not remaining & set(victims), "victims must be gone"
        assert len(remaining) == 12
        assert execu.check_once() is False  # queue empty: idempotent
        # the executor's confirmations dispatched victim_gone decisions:
        # the gate is open and the member bind now lands
        res = ext.gang.reservation("default", "vip")
        assert res is not None and not ext.gang.terminating_victims_of(res)
        ext.bind("vip-0", "default", "", target)
        assert ext.state.allocation("default/vip-0") is not None


def test_eviction_executor_requeues_blocked_and_failed():
    """A PDB-blocked (429) or transiently-failing eviction is requeued and
    retried next poll — never dropped: the ledger already freed the chips,
    so losing the eviction would double-allocate."""
    from collections import deque
    from types import SimpleNamespace

    api = apisrv.FakeApiServer()
    for n in ("a", "b"):
        api.upsert_pod({"metadata": {"name": n, "namespace": "default"}})
    api.pdb_blocked.add("default/b")
    ext = SimpleNamespace(pending_evictions=deque(["default/a", "default/b"]))

    execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
    assert execu.drain() == ["default/a"]
    assert list(ext.pending_evictions) == ["default/b"]
    assert (execu.evicted, execu.blocked) == (1, 1)

    api.pdb_blocked.clear()  # the PDB lifts: the retry lands
    assert execu.drain() == ["default/b"]
    assert not ext.pending_evictions
    assert execu.evicted == 2

    class DownApi:
        def evict_pod(self, namespace, name):
            raise apisrv.ApiServerError("apiserver unreachable")

    ext.pending_evictions.append("default/c")
    down = apisrv.EvictionExecutor(ext, DownApi(), poll_seconds=999)
    assert down.drain() == []
    assert list(ext.pending_evictions) == ["default/c"]
    assert down.failures == 1


def test_rest_eviction_subresource():
    """RestApiServer.evict_pod POSTs the policy/v1 Eviction subresource
    and maps the apiserver's verdicts: 2xx/404 -> True (gone), 429 -> False
    (PDB says retry later), others raise. delete_pod DELETEs, tolerating
    404."""
    import http.server
    from collections import deque as _dq

    seen = []
    post_codes = _dq([201, 429, 404, 500])

    class Handler(http.server.BaseHTTPRequestHandler):
        def _reply(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n)) if n else None
            seen.append(("POST", self.path, body))
            self._reply(post_codes.popleft(), {})

        def do_DELETE(self):
            seen.append(("DELETE", self.path, None))
            self._reply(404 if "gone" in self.path else 200, {})

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="sekrit",
        )
        assert api.evict_pod("default", "p0") is True    # 201: evicted
        assert api.evict_pod("default", "p0") is False   # 429: PDB
        assert api.evict_pod("default", "p0") is True    # 404: already gone
        with pytest.raises(apisrv.ApiServerError) as ei:  # 500: surfaced
            api.evict_pod("default", "p0")
        assert ei.value.code == 500
        api.delete_pod("default", "p1")        # 200
        api.delete_pod("default", "gone-p2")   # 404 tolerated
    finally:
        httpd.shutdown()

    method, path, body = seen[0]
    assert (method, path) == (
        "POST", "/api/v1/namespaces/default/pods/p0/eviction"
    )
    assert body == {
        "apiVersion": "policy/v1",
        "kind": "Eviction",
        "metadata": {"name": "p0", "namespace": "default"},
    }
    assert seen[4][:2] == ("DELETE", "/api/v1/namespaces/default/pods/p1")
    assert seen[5][:2] == ("DELETE", "/api/v1/namespaces/default/pods/gone-p2")


def test_eviction_executor_waits_for_graceful_termination():
    """A 2xx on the Eviction subresource only STARTS graceful termination
    — the pod keeps its devices until its containers stop. The executor
    must keep tracking the key (without re-POSTing) and count it evicted
    only once the pod object is actually gone."""
    from collections import deque
    from types import SimpleNamespace

    class GracefulApi:
        def __init__(self):
            self.pods = {"default/a": {
                "metadata": {"name": "a", "namespace": "default"}}}
            self.evict_calls = 0

        def evict_pod(self, namespace, name):
            self.evict_calls += 1
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:  # the apiserver stamps deletionTimestamp
                pod["metadata"]["deletionTimestamp"] = "2026-07-29T00:00:00Z"
            return True  # accepted; pod still terminating

        def get_pod(self, namespace, name):
            return self.pods.get(f"{namespace}/{name}")

    api = GracefulApi()
    ext = SimpleNamespace(pending_evictions=deque(["default/a"]))
    execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
    assert execu.drain() == []            # accepted, not yet gone
    assert execu.evicted == 0
    assert execu.depth() == 1             # still tracked (terminating)
    assert not ext.pending_evictions      # but no eviction re-POST
    assert execu.drain() == []            # grace period still running
    assert api.evict_calls == 1
    api.pods.clear()                      # termination completes
    assert execu.drain() == ["default/a"]
    assert execu.evicted == 1
    assert execu.depth() == 0

    # a controller recreating the same name (fresh object, no
    # deletionTimestamp) must confirm too — the ORIGINAL victim is gone;
    # waiting on the newcomer would track a phantom eviction forever
    ext.pending_evictions.append("default/a")
    api.pods["default/a"] = {
        "metadata": {"name": "a", "namespace": "default"}}
    execu2 = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
    execu2.drain()                         # accept: stamps the original
    api.pods["default/a"] = {              # controller replaces it
        "metadata": {"name": "a", "namespace": "default"}}
    assert execu2.drain() == ["default/a"]
    assert execu2.depth() == 0


def _gang_schedule_body(pod_name, node_objects, group, priority=100):
    annotations = dict(codec.pod_group_annotations(group))
    pod_obj = {
        "metadata": {
            "name": pod_name, "namespace": "default",
            "uid": f"uid-{pod_name}", "annotations": annotations,
        },
        "spec": {
            "priority": priority,
            "containers": [{
                "name": "main",
                "resources": {"requests": {"qiniu.com/tpu": "1"}},
            }],
        },
    }
    return pod_obj, {"Pod": pod_obj, "Nodes": {"Items": node_objects}}


def test_gang_bind_waits_for_graceful_victim_termination():
    """The victim-overlap capstone: with victims that terminate GRACEFULLY
    (deletionTimestamp stamped, object lingers — the real apiserver's
    behavior), a gang bind onto preempted chips retries until the victim
    object is actually gone. No member ever binds while a victim's
    containers still hold the chips."""
    from tpukube.core.types import PodGroup

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        for i in range(16):
            pod = c.make_pod(f"s-{i}", tpu=1, priority=5)
            c.schedule(pod)
            api.upsert_pod(pod)
            api.graceful.add(f"default/s-{i}")  # real-world termination
        ext = c.extender
        ext.evict_precheck = (
            lambda pod_key: api.evict_pod(*pod_key.split("/", 1),
                                          dry_run=True)
        )
        execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
        group = PodGroup("vip", min_member=4)
        pod_obj, fbody = _gang_schedule_body(
            "vip-0", c.node_objects(), group
        )
        fres = ext.handle("filter", fbody)
        assert fres["NodeNames"], fres.get("Error")
        target = fres["NodeNames"][0]
        bind_body = {
            "PodName": "vip-0", "PodNamespace": "default",
            "PodUID": "uid-vip-0", "Node": target,
        }
        # first bind: plan executes, bind waits
        bres = ext.handle("bind", bind_body)
        assert "finish terminating" in bres["Error"]
        victims = [pk for pk in ext.pending_evictions]
        assert len(victims) == 4

        # the executor accepts the evictions; victims are TERMINATING —
        # objects linger with deletionTimestamp, so binds stay gated
        execu.check_once()
        assert execu.evicted == 0 and execu.depth() == 4
        # the operator can SEE why the gang is not binding
        (snap,) = ext.gang_snapshot()
        assert snap["victims_terminating"] == 4
        assert snap["victims_pending"] == 0
        for pk in victims:
            ns, name = pk.split("/", 1)
            assert api.get_pod(ns, name)["metadata"]["deletionTimestamp"]
        bres = ext.handle("bind", bind_body)
        assert "victim" in bres["Error"]
        assert ext.state.allocation("default/vip-0") is None

        # two victims finish: still gated (all-or-nothing on the gate)
        for pk in victims[:2]:
            api.finish_termination(*pk.split("/", 1))
        execu.check_once()
        assert execu.evicted == 2
        bres = ext.handle("bind", bind_body)
        assert "victim" in bres["Error"]

        # the rest finish: the gate opens and the member binds
        for pk in victims[2:]:
            api.finish_termination(*pk.split("/", 1))
        execu.check_once()
        assert execu.evicted == 4
        bres = ext.handle("bind", bind_body)
        assert not bres.get("Error"), bres
        assert ext.state.allocation("default/vip-0") is not None
        # the whole sequence — including the victim_gone confirmations —
        # replays deterministically
        from tpukube import trace as trace_mod
        assert trace_mod.replay(ext.trace.events(), config=cfg) == []


def test_reconcile_loop_watch_mode_folds_report_on_event():
    """Watch-mode AllocReconcileLoop: a kubelet divergence report
    (alloc-actual annotation) is folded into the ledger by the MODIFIED
    event that carries it — no LIST poll — and the clearing PATCH's own
    follow-up event no-ops instead of looping."""
    import time as _time

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        pod = c.make_pod("p0", tpu=1)
        api.upsert_pod(pod)
        c.extender.binder = apisrv.pod_binder(api)
        _, alloc = c.schedule(pod)
        view = c.extender.state.node(alloc.node_name)
        free = [ch for ch in view.info.chips
                if f"tpu-{ch.index}" not in view.used_ids]
        actual_id = f"tpu-{free[0].index}"

        loop = apisrv.AllocReconcileLoop(c.extender, api, poll_seconds=999)
        assert loop._use_watch
        loop.start()
        try:
            # the node agent reports what the kubelet REALLY allocated
            api.patch_pod_annotations(
                "default", "p0",
                {apisrv.ANNO_ALLOC_ACTUAL:
                 apisrv.encode_alloc_actual([actual_id])},
            )
            deadline = _time.monotonic() + 5
            while loop.reconciled == 0 and _time.monotonic() < deadline:
                _time.sleep(0.01)
            assert loop.reconciled == 1
            assert c.extender.state.allocation(
                "default/p0").device_ids == [actual_id]
            annos = api.get_pod("default", "p0")["metadata"]["annotations"]
            assert apisrv.ANNO_ALLOC_ACTUAL not in annos  # report cleared
            fixed = codec.decode_alloc(annos[codec.ANNO_ALLOC])
            assert fixed.device_ids == [actual_id]
            _time.sleep(0.1)  # the clearing PATCH's event must not loop
            assert loop.reconciled == 1
        finally:
            loop.stop()


def test_restart_mid_victim_termination_is_safe():
    """Extender restart while preemption victims terminate: the rebuilt
    ledger restores the still-terminating victims (their objects carry
    only a deletionTimestamp — containers may still hold the chips), so
    no placement can overlap them; the gang re-plans preemption from
    scratch, re-executes against the already-terminating victims, and
    binds only once their objects are confirmed gone."""
    from tpukube.core.types import PodGroup
    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        for obj in c.node_objects():
            api.patch_node_annotations(obj["metadata"]["name"],
                                       obj["metadata"]["annotations"])
        for i in range(16):
            pod = c.make_pod(f"s-{i}", tpu=1, priority=5)
            c.schedule(pod)  # mutates pod: nodeName + alloc annotation
            api.upsert_pod(pod)
            api.graceful.add(f"default/s-{i}")
        ext = c.extender
        ext.evict_precheck = (
            lambda pk: api.evict_pod(*pk.split("/", 1), dry_run=True)
        )
        execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
        group = PodGroup("vip", min_member=4)
        _, fbody = _gang_schedule_body("vip-0", c.node_objects(), group)
        fres = ext.handle("filter", fbody)
        target = fres["NodeNames"][0]
        bind_body = {"PodName": "vip-0", "PodNamespace": "default",
                     "PodUID": "uid-vip-0", "Node": target}
        bres = ext.handle("bind", bind_body)
        assert "finish terminating" in bres["Error"]
        execu.check_once()  # evictions accepted; victims now TERMINATING
        victims = sorted(
            f"{p['metadata']['namespace']}/{p['metadata']['name']}"
            for p in api.list_pods()
            if p["metadata"].get("deletionTimestamp")
        )
        assert len(victims) == 4

        # ---- CRASH + RESTART ------------------------------------------
        fresh = Extender(cfg)
        fresh.evict_precheck = ext.evict_precheck
        restored = apisrv.rebuild_extender(fresh, api)
        # the terminating victims' ledger entries are RESTORED: their
        # containers may still hold the chips, so nothing may bind there
        assert {v for v in victims} <= {
            a.pod_key for a in fresh.state.allocations()
        }
        assert restored == 16
        # the uncommitted gang reservation died with the process, and no
        # eviction queue survived — nothing is half-executed
        assert fresh.gang.reservation("default", "vip") is None
        assert not fresh.pending_evictions

        # the gang's next cycle re-plans preemption; victims are already
        # terminating, so re-eviction is an idempotent accept
        execu2 = apisrv.EvictionExecutor(fresh, api, poll_seconds=999)
        _, fbody2 = _gang_schedule_body("vip-0", c.node_objects(), group)
        fres2 = fresh.handle("filter", fbody2)
        assert fres2["NodeNames"], fres2.get("Error")
        bres2 = fresh.handle("bind", {
            "PodName": "vip-0", "PodNamespace": "default",
            "PodUID": "uid-vip-0", "Node": fres2["NodeNames"][0],
        })
        assert "finish terminating" in bres2["Error"]
        execu2.check_once()
        bres2 = fresh.handle("bind", {
            "PodName": "vip-0", "PodNamespace": "default",
            "PodUID": "uid-vip-0", "Node": fres2["NodeNames"][0],
        })
        assert "victim" in bres2["Error"]  # still gated mid-grace

        # terminations finish; the new executor confirms; the bind lands
        for p in list(api.list_pods()):
            if p["metadata"].get("deletionTimestamp"):
                api.finish_termination(p["metadata"]["namespace"],
                                      p["metadata"]["name"])
        execu2.check_once()
        fres3 = fresh.handle("filter", fbody2)
        bres3 = fresh.handle("bind", {
            "PodName": "vip-0", "PodNamespace": "default",
            "PodUID": "uid-vip-0", "Node": fres3["NodeNames"][0],
        })
        assert not bres3.get("Error"), bres3
        assert fresh.state.allocation("default/vip-0") is not None


def test_pdb_blocked_victim_refuses_preemption_plan():
    """A preemption plan with a PDB-blocked victim is refused at the
    precheck, BEFORE any irreversible eviction: no victim is touched, the
    gang never half-binds, and the reservation TTLs out cleanly."""
    import time as _time

    from tpukube.core.types import PodGroup

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        for i in range(16):
            pod = c.make_pod(f"s-{i}", tpu=1, priority=5)
            c.schedule(pod)
            api.upsert_pod(pod)
        ext = c.extender
        ext.evict_precheck = (
            lambda pod_key: api.evict_pod(*pod_key.split("/", 1),
                                          dry_run=True)
        )
        group = PodGroup("vip", min_member=4)
        _, fbody = _gang_schedule_body("vip-0", c.node_objects(), group)
        fres = ext.handle("filter", fbody)
        assert fres["NodeNames"]
        res = ext.gang.reservation("default", "vip")
        assert res is not None and res.pending_victims
        victim_keys = {
            pk for w in res.pending_victims for pk in w.pod_keys
        }
        blocked_key = sorted(victim_keys)[0]
        api.pdb_blocked.add(blocked_key)

        target = fres["NodeNames"][0]
        bres = ext.handle("bind", {
            "PodName": "vip-0", "PodNamespace": "default",
            "PodUID": "uid-vip-0", "Node": target,
        })
        assert "PodDisruptionBudget" in bres["Error"]
        assert blocked_key in bres["Error"]
        # nothing irreversible happened: no eviction queued, every victim
        # still holds its chips, the plan is still pending
        assert not ext.pending_evictions
        assert ext.preemptions == 0
        assert all(
            ext.state.allocation(f"default/s-{i}") is not None
            for i in range(16)
        )
        assert res.pending_victims

        # the reservation TTLs out without costing anyone anything
        ttl = c.config.reservation_ttl_seconds
        rolled = ext.gang.sweep(now=_time.monotonic() + ttl + 1)
        assert ("default", "vip") in rolled
        assert not ext.pending_evictions


def test_confirm_deleted_outrunning_drain_still_counts():
    """An instantly-deleted victim's DELETED event can reach the
    lifecycle watch BEFORE drain() returns from evict_pod: the
    pre-registration (_expecting) must catch that confirm so the gang's
    victim_gone fires immediately instead of after the 30s GET net —
    and nothing is double-counted or requeued afterwards."""
    from collections import deque
    from types import SimpleNamespace

    gone: list[str] = []

    class ExtStub(SimpleNamespace):
        def handle(self, kind, body):
            gone.append(body["pod_key"])
            return {"cleared": True}

    ext = ExtStub(pending_evictions=deque(["default/v"]))
    execu_box: list = []

    class RacingApi:
        """evict_pod delivers the DELETED confirmation synchronously
        (the watch thread winning the race) before returning."""

        def evict_pod(self, namespace, name, dry_run=False):
            execu_box[0].confirm_deleted(f"{namespace}/{name}")
            return True  # 404-ish: pod already gone

        def get_pod(self, namespace, name):
            return None

    execu = apisrv.EvictionExecutor(ext, RacingApi(), poll_seconds=999)
    execu_box.append(execu)
    assert execu.drain() == []        # confirm already landed mid-call
    assert execu.evicted == 1
    assert gone == ["default/v"]
    assert execu.depth() == 0         # not tracked, not requeued
    assert execu.drain() == []        # idempotent; no double count
    assert execu.evicted == 1
    assert execu.oldest_age_seconds() == 0.0


def test_lifecycle_watch_confirms_evictions():
    """Termination-detection unification: the lifecycle loop's DELETED
    event confirms an in-flight eviction directly — no GET poll — and
    dispatches the victim_gone decision that unblocks gated gangs."""
    from collections import deque
    from types import SimpleNamespace

    api = apisrv.FakeApiServer()
    api.graceful.add("default/v")
    api.upsert_pod({"metadata": {"name": "v", "namespace": "default",
                                 "uid": "uid-v"}, "spec": {}})
    gone: list[str] = []

    class ExtStub(SimpleNamespace):
        def handle(self, kind, body):
            assert kind == "victim_gone"
            gone.append(body["pod_key"])
            return {"cleared": True}

    ext = ExtStub(pending_evictions=deque(["default/v"]),
                  state=SimpleNamespace(
                      allocation=lambda key: None, allocations=lambda: []),
                  )
    execu = apisrv.EvictionExecutor(ext, api, poll_seconds=999)
    execu.drain()
    assert execu.depth() == 1 and execu.evicted == 0
    assert execu.oldest_age_seconds() >= 0.0

    lifecycle = apisrv.PodLifecycleReleaseLoop(
        ext, api, poll_seconds=999, use_watch=False, evictions=execu,
    )
    # the pod object finally goes away; the lifecycle loop sees the
    # DELETED event and confirms the eviction without any GET
    pod = api.get_pod("default", "v")
    api.finish_termination("default", "v")
    lifecycle._apply_watch_event("DELETED", pod)
    assert execu.evicted == 1
    assert execu.depth() == 0
    assert gone == ["default/v"]
    assert execu.oldest_age_seconds() == 0.0


def test_ambiguous_intents_defer_to_local_choice(tmp_path):
    """Two identical pending pods (VERDICT round-2 weak #4): the
    preference query carries no pod identity, so steering would be a coin
    flip onto the other pod's plan. preferred() must refuse (local
    heuristic answers), and a non-plan Allocate must not be attributed to
    either plan — zero manufactured divergences."""
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer, FakeKubelet

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    with TpuDeviceManager(cfg, host="host-0-0-0") as device, \
            DevicePluginServer(cfg, device) as server, \
            FakeKubelet(str(tmp_path)) as kubelet:
        server.register_with_kubelet()
        devs = sorted(kubelet.wait_for_devices(server.resource_name, 4))
        baseline = kubelet.preferred(server.resource_name, devs, 2)

        server.intents.sync({
            "default/a": ["tpu-0", "tpu-1"],
            "default/b": ["tpu-2", "tpu-3"],
        })
        # kubelet asks twice: both times the ambiguous plans defer to the
        # local heuristic instead of handing out pod A's plan
        for _ in range(2):
            got = kubelet.preferred(server.resource_name, devs, 2)
            assert sorted(got) == sorted(baseline)

        # kubelet allocates something that is NEITHER plan: consume must
        # refuse attribution (no divergence report, both plans pending)
        kubelet.allocate(server.resource_name, ["tpu-1", "tpu-2"])
        assert server.divergences == 0
        assert server.intents.depth() == 2

        # once one plan is satisfied exactly, the remaining single plan
        # steers again — ambiguity was the only blocker
        kubelet.allocate(server.resource_name, ["tpu-0", "tpu-1"])
        assert server.intents.depth() == 1
        steered = kubelet.preferred(server.resource_name, devs, 2)
        assert sorted(steered) == ["tpu-2", "tpu-3"]
        assert server.divergences == 0


# -- bind effector -----------------------------------------------------------

def test_bind_effector_creates_real_binding():
    """With bindVerb delegated to the extender, a successful /bind must
    bind THROUGH the apiserver — nodeName set, alloc annotation persisted.
    The webhook response's annotations alone start nothing on a real
    cluster."""
    from tpukube.core.types import PodGroup

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        c.extender.binder = apisrv.pod_binder(api)
        pod = c.make_pod("p0", tpu=2)
        api.upsert_pod(pod)
        node, alloc = c.schedule(pod)
        bound = api.get_pod("default", "p0")
        assert bound["spec"]["nodeName"] == node
        assert codec.ANNO_ALLOC in bound["metadata"]["annotations"]
        assert ("bind", "default/p0") in api.patch_log

        # gang members bind through the same effector — and their gang
        # env rides BOTH the alloc blob and the per-key annotations the
        # downward API projects (deploy/gang-job-example.yaml)
        group = PodGroup("g", min_member=2)
        for i in range(2):
            gp = c.make_pod(f"g-{i}", tpu=1, group=group)
            api.upsert_pod(gp)
            c.schedule(gp)
        for i in range(2):
            bound = api.get_pod("default", f"g-{i}")
            assert bound["spec"]["nodeName"]
            annos = bound["metadata"]["annotations"]
            alloc_env = codec.decode_alloc(annos[codec.ANNO_ALLOC]).env
            assert alloc_env  # gang members carry coordination env
            for var, anno in codec.GANG_ENV_TO_ANNO.items():
                assert annos[anno] == alloc_env[var]


def test_bind_effector_failure_rolls_back_ledger():
    """A failed Binding POST must not leave the ledger claiming the pod is
    bound — undo and let the scheduler re-run the cycle."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        c.extender.binder = apisrv.pod_binder(api)
        pod = c.make_pod("p0", tpu=1)  # NOT upserted into the api: 404
        with pytest.raises(RuntimeError, match="apiserver bind failed"):
            c.schedule(pod, retries=2)
        assert c.extender.state.allocation("default/p0") is None
        assert c.utilization() == 0.0

        api.upsert_pod(pod)  # pod appears; the retried cycle binds clean
        node, _ = c.schedule(pod)
        assert api.get_pod("default", "p0")["spec"]["nodeName"] == node
        assert c.extender.state.allocation("default/p0") is not None


def test_rest_bind_pod_posts_binding_subresource():
    """RestApiServer.bind_pod PATCHes the alloc annotation FIRST (the pod
    is still Pending: intent lands before the kubelet's Allocate, and a
    partial failure leaves the pod unbound/retryable), then POSTs the v1
    Binding; a 409 on the Binding (already bound) is idempotent success
    ONLY when the pod is bound to the requested node."""
    import http.server

    seen = []
    post_codes = []
    bound_node = [""]

    class Handler(http.server.BaseHTTPRequestHandler):
        def _reply(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            seen.append(("POST", self.path, json.loads(self.rfile.read(n))))
            self._reply(post_codes.pop(0), {})

        def do_PATCH(self):
            n = int(self.headers.get("Content-Length", 0))
            seen.append(("PATCH", self.path, json.loads(self.rfile.read(n))))
            self._reply(200, {})

        def do_GET(self):  # the 409 path verifies via get_pod
            self._reply(200, {
                "metadata": {"name": "p0", "namespace": "default"},
                "spec": {"nodeName": bound_node[0]},
            })

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="t",
        )
        post_codes.append(201)
        api.bind_pod("default", "p0", "host-3-1-0", {"k": "v"})
        # retry of a pod already bound to the SAME node: success (POST
        # 409 re-verified against the bound node)
        post_codes.append(409)
        bound_node[0] = "host-3-1-0"
        api.bind_pod("default", "p0", "host-3-1-0", {"k": "v"})
        # pod bound ELSEWHERE: the pre-check conflicts BEFORE any PATCH —
        # a pod running on another host is never touched
        bound_node[0] = "host-0-0-0"
        patches_before = sum(1 for e in seen if e[0] == "PATCH")
        with pytest.raises(apisrv.ApiServerError, match="already bound"):
            api.bind_pod("default", "p0", "host-3-1-0", {"k": "v"})
        assert sum(1 for e in seen if e[0] == "PATCH") == patches_before
        bound_node[0] = ""
        post_codes.append(500)  # a real failure still surfaces
        with pytest.raises(apisrv.ApiServerError):
            api.bind_pod("default", "p0", "host-3-1-0", {"k": "v"})
    finally:
        httpd.shutdown()

    # annotation PATCH precedes the Binding POST
    assert seen[0][:2] == ("PATCH", "/api/v1/namespaces/default/pods/p0")
    assert seen[0][2] == {"metadata": {"annotations": {"k": "v"}}}
    method, path, body = seen[1]
    assert (method, path) == (
        "POST", "/api/v1/namespaces/default/pods/p0/binding"
    )
    assert body["kind"] == "Binding"
    assert body["target"] == {
        "apiVersion": "v1", "kind": "Node", "name": "host-3-1-0",
    }


def test_bind_effector_failure_uncommits_quorum():
    """When the QUORUM member's Binding POST fails, the gang's commit must
    be reverted: no committed-below-quorum reservation exempt from the
    sweep, and no north-star latency sample for a commit that never
    happened on the cluster."""
    from tpukube.core.types import PodGroup

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        c.extender.binder = apisrv.pod_binder(api)
        group = PodGroup("g", min_member=2)
        p0 = c.make_pod("g-0", tpu=1, group=group)
        api.upsert_pod(p0)
        c.schedule(p0)
        res = c.extender.gang.reservation("default", "g")
        assert res is not None and not res.committed

        p1 = c.make_pod("g-1", tpu=1, group=group)  # NOT in the api: 404
        with pytest.raises(RuntimeError, match="apiserver bind failed"):
            c.schedule(p1, retries=2)
        res = c.extender.gang.reservation("default", "g")
        assert res is not None
        assert not res.committed, "quorum bind failed: commit must revert"
        assert len(c.extender.gang.commit_latencies) == 0

        api.upsert_pod(p1)  # the pod appears; the retried cycle commits
        c.schedule(p1)
        res = c.extender.gang.reservation("default", "g")
        assert res is not None and res.committed
        assert len(c.extender.gang.commit_latencies) == 1


# -- restart rebuild over the apiserver channel ------------------------------

def test_rebuild_extender_from_apiserver():
    """SURVEY §6 restart story on the REAL channel: a fresh extender
    reconstructs ledger + gang reservations purely from what the
    apiserver holds (node topology annotations + pod alloc/pod-group
    annotations); malformed entries are skipped, not fatal."""
    from tpukube.core.types import PodGroup
    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        group = PodGroup("g", min_member=4)
        for i in range(4):
            pod = c.make_pod(f"g-{i}", tpu=1, group=group)
            c.schedule(pod)
            api.upsert_pod(pod)
        pod = c.make_pod("solo", tpu=2)
        c.schedule(pod)
        api.upsert_pod(pod)
        for obj in c.node_objects():
            api.patch_node_annotations(
                obj["metadata"]["name"], obj["metadata"]["annotations"]
            )
        util_before = c.utilization()

        # a junk pod annotation and a junk node must be skipped loudly,
        # never abort the rebuild
        api.upsert_pod({"metadata": {
            "name": "junk", "namespace": "default",
            "annotations": {codec.ANNO_ALLOC: "{not json"},
        }})
        api.patch_node_annotations(
            "junk-node", {codec.ANNO_NODE_TOPOLOGY: "{not json"}
        )

        fresh = Extender(cfg)
        restored = apisrv.rebuild_extender(fresh, api)
        assert restored == 5
        assert fresh.state.utilization() == pytest.approx(util_before)
        res = fresh.gang.reservation("default", "g")
        assert res is not None and res.committed
        assert len(res.assigned) == 4


# -- pod-lifecycle release loop ----------------------------------------------

def test_lifecycle_release_via_sim_harness():
    """The sim's delete/complete paths run the SAME release loop a real
    extender daemon runs — no manual extender.release side channel."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        p0 = c.make_pod("a", tpu=1)
        p1 = c.make_pod("b", tpu=1)
        c.schedule(p0)
        c.schedule(p1)
        assert c.extender.state.allocation("default/a") is not None

        c.delete_pod("a")  # object gone -> released
        assert c.extender.state.allocation("default/a") is None

        c.complete_pod("b")  # phase Succeeded, object LINGERS -> released
        assert c.extender.state.allocation("default/b") is None
        assert "default/b" in c.pods  # the completed pod object remains
        assert c.extender.state.utilization() == 0.0
        assert c._lifecycle.released == 2
        assert c._lifecycle.check_once() is False  # idempotent


def test_lifecycle_watch_mode_releases_on_delete():
    """Watch-mode loop against the fake apiserver: a bound pod's DELETED
    event frees its chips with no poll and no manual release."""
    import time

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        c.extender.binder = apisrv.pod_binder(api)
        pod = c.make_pod("w0", tpu=1)
        api.upsert_pod(pod)
        c.schedule(pod)
        assert c.extender.state.allocation("default/w0") is not None

        loop = apisrv.PodLifecycleReleaseLoop(
            c.extender, api, poll_seconds=0.05
        )
        assert loop._use_watch
        loop.start()
        try:
            api.delete_pod("default", "w0")
            deadline = time.monotonic() + 5
            while (time.monotonic() < deadline
                   and c.extender.state.allocation("default/w0")):
                time.sleep(0.02)
            assert c.extender.state.allocation("default/w0") is None
            assert loop.released == 1
        finally:
            loop.stop()


def test_lifecycle_resync_confirms_before_releasing():
    """A list snapshot can predate a just-bound pod's creation; the resync
    must GET-confirm absence before releasing, or it would free a LIVE
    pod's chips out from under it."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        pod = c.make_pod("young", tpu=1)
        c.schedule(pod)

        class StaleListApi:
            """List is stale (missing the pod); GET still finds it."""

            def list_pods(self, node_name=None):
                return []

            def get_pod(self, namespace, name):
                return c.pods.get(f"{namespace}/{name}")

        loop = apisrv.PodLifecycleReleaseLoop(
            c.extender, StaleListApi(), use_watch=False
        )
        assert loop.check_once() is False
        assert c.extender.state.allocation("default/young") is not None

        # once the pod is REALLY gone, the same resync releases it
        del c.pods["default/young"]
        assert loop.check_once() is True
        assert c.extender.state.allocation("default/young") is None


def test_lifecycle_watch_event_semantics():
    """Event rules: DELETED releases; terminal phase releases; Running
    MODIFIED and unknown pods do not."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for name in ("a", "b", "c"):
            c.schedule(c.make_pod(name, tpu=1))
        loop = c._lifecycle

        def pod_obj(name, phase=None):
            obj = {"metadata": {"name": name, "namespace": "default"}}
            if phase:
                obj["status"] = {"phase": phase}
            return obj

        loop._apply_watch_event("MODIFIED", pod_obj("a", "Running"))
        assert c.extender.state.allocation("default/a") is not None
        loop._apply_watch_event("MODIFIED", pod_obj("a", "Failed"))
        assert c.extender.state.allocation("default/a") is None
        loop._apply_watch_event("DELETED", pod_obj("b"))
        assert c.extender.state.allocation("default/b") is None
        # a stranger pod's deletion is a no-op, not an error
        loop._apply_watch_event("DELETED", pod_obj("stranger"))
        assert loop.released == 2


def test_lifecycle_uid_guard_spares_recreated_pod():
    """Pod names recur (StatefulSet members). A stale lifecycle signal
    about the OLD incarnation must not free the chips a recreated,
    re-bound pod is holding — and a same-name pod with a different uid
    proves the ledger's incarnation is gone (phantom-allocation cure)."""
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        pod = c.make_pod("web-0", tpu=1)
        c.schedule(pod)
        alloc = c.extender.state.allocation("default/web-0")
        assert alloc is not None and alloc.uid == "uid-default-web-0"
        loop = c._lifecycle

        stale = {"metadata": {"name": "web-0", "namespace": "default",
                              "uid": "uid-of-the-OLD-incarnation"}}
        loop._apply_watch_event("DELETED", stale)
        assert c.extender.state.allocation("default/web-0") is not None
        loop._apply_watch_event(
            "MODIFIED",
            {"metadata": {"name": "web-0", "namespace": "default",
                          "uid": "uid-of-the-OLD-incarnation"},
             "status": {"phase": "Failed"}},
        )
        assert c.extender.state.allocation("default/web-0") is not None
        assert loop.released == 0

        # resync: the store now holds a RECREATED web-0 (different uid,
        # not yet bound) — the old incarnation's ledger entry must go, or
        # the newcomer's bind 409s forever
        c.pods["default/web-0"]["metadata"]["uid"] = "uid-recreated"
        assert loop.check_once() is True
        assert c.extender.state.allocation("default/web-0") is None
        assert loop.released == 1

        # the matching-uid event releases normally
        pod2 = c.make_pod("web-1", tpu=1)
        c.schedule(pod2)
        loop._apply_watch_event(
            "DELETED",
            {"metadata": {"name": "web-1", "namespace": "default",
                          "uid": "uid-default-web-1"}},
        )
        assert c.extender.state.allocation("default/web-1") is None


def test_rebuild_skips_dead_and_unbound_pods():
    """The restart path must not re-import the leak: terminal-phase pods,
    unbound alloc residue (bind partial failure), and node-mismatched
    annotations are skipped; a gracefully-TERMINATING pod is restored
    (its containers still hold the chips until it is really gone)."""
    import copy as copymod

    from tpukube.sched.extender import Extender

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        specs = {}
        for name in ("live", "done", "terminating", "residue", "moved"):
            pod = c.make_pod(name, tpu=1)
            c.schedule(pod)
            specs[name] = copymod.deepcopy(pod)
        for obj in c.node_objects():
            api.patch_node_annotations(
                obj["metadata"]["name"], obj["metadata"]["annotations"]
            )

        specs["done"].setdefault("status", {})["phase"] = "Succeeded"
        specs["terminating"]["metadata"]["deletionTimestamp"] = (
            "2026-07-30T00:00:00Z"
        )
        del specs["residue"]["spec"]["nodeName"]  # Binding POST never landed
        other = [n for n in c.nodes
                 if n != specs["moved"]["spec"]["nodeName"]][0]
        specs["moved"]["spec"]["nodeName"] = other
        for pod in specs.values():
            api.upsert_pod(pod)

        fresh = Extender(cfg)
        assert apisrv.rebuild_extender(fresh, api) == 2
        assert fresh.state.allocation("default/live") is not None
        assert fresh.state.allocation("default/terminating") is not None
        for name in ("done", "residue", "moved"):
            assert fresh.state.allocation(f"default/{name}") is None, name


# -- watch channel -----------------------------------------------------------

def test_rest_watch_pods_streams_events():
    """RestApiServer.watch_pods speaks the k8s watch protocol: chunked
    stream of {"type", "object"} lines, field-selected, ending when the
    server closes at timeoutSeconds."""
    import http.server

    paths = []

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            paths.append(self.path)
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(data: bytes):
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()

            for i, etype in enumerate(("ADDED", "MODIFIED", "DELETED")):
                chunk(json.dumps({
                    "type": etype,
                    "object": {"metadata": {"name": f"p{i}"}},
                }).encode() + b"\n")
            chunk(b"{not json\n")  # garbage line must be skipped
            self.wfile.write(b"0\r\n\r\n")

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="t",
        )
        events = list(api.watch_pods("n1", timeout_seconds=30))
        list(api.watch_pods("n1", timeout_seconds=30,
                            resource_version="4 2"))
        node_events = list(api.watch_nodes(timeout_seconds=30,
                                           resource_version="7"))
    finally:
        httpd.shutdown()
    assert [(e, p["metadata"]["name"]) for e, p in events] == [
        ("ADDED", "p0"), ("MODIFIED", "p1"), ("DELETED", "p2"),
    ]
    assert paths[0] == (
        "/api/v1/pods?watch=1&timeoutSeconds=30"
        "&fieldSelector=spec.nodeName%3Dn1"
    )
    assert paths[1].endswith("&resourceVersion=4%202")  # informer contract
    # the node watch rides the same transport against /api/v1/nodes
    assert len(node_events) == 3
    assert paths[2] == (
        "/api/v1/nodes?watch=1&timeoutSeconds=30&resourceVersion=7"
    )


def test_fake_watch_replays_list_to_watch_gap():
    """The fake honors the informer contract's resourceVersion: a
    mutation landing between list_pods_with_rv and watch_pods is REPLAYED
    at the watch's start, not silently dropped (the REST path closes this
    gap with the resourceVersion parameter; the fake must too, or
    watch-mode tests pass while hiding a real race)."""
    api = apisrv.FakeApiServer()
    api.upsert_pod({"metadata": {"name": "a", "namespace": "default",
                                 "uid": "u-a"}, "spec": {}})
    pods, rv = api.list_pods_with_rv()
    assert [p["metadata"]["name"] for p in pods] == ["a"]

    # the gap: a deletion no live subscription sees
    api.delete_pod("default", "a")

    box: list = []
    gen = api.watch_pods(resource_version=rv, handle_box=box,
                         timeout_seconds=5)
    etype, pod = next(gen)
    assert (etype, pod["metadata"]["name"]) == ("DELETED", "a")
    box[0].close()
    assert list(gen) == []

    # without a version the watch starts at "now": nothing is replayed
    box2: list = []
    gen2 = api.watch_pods(handle_box=box2, timeout_seconds=5)
    box2[0].close()
    assert list(gen2) == []

    # a version older than the bounded history answers 410 Gone (the
    # real apiserver's contract) instead of silently skipping the
    # evicted events — the informer's reconnect then resyncs fresh
    api._history.popleft()  # evict the oldest retained event
    with pytest.raises(apisrv.ApiServerError) as e:
        api.watch_pods(resource_version="0", timeout_seconds=5)
    assert e.value.code == 410


def test_intent_watcher_watch_mode(tmp_path):
    """Watch-mode AllocIntentWatcher: intents land as events arrive (no
    poll-interval race against the kubelet's Allocate), DELETED removes,
    and a closed stream resyncs+reconnects."""
    import queue
    import time as _time

    from tpukube.core.types import AllocResult, TopologyCoord
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer

    class WatchApi:
        def __init__(self):
            self.pods = []
            self.events: queue.Queue = queue.Queue()
            self.connects = 0

        def list_pods(self, node_name=None):
            return list(self.pods)

        def watch_pods(self, node_name=None, timeout_seconds=300):
            self.connects += 1
            while True:
                ev = self.events.get()
                if ev is None:  # server closes the stream
                    return
                yield ev

    def pod_with_alloc(name, ids):
        alloc = AllocResult(
            pod_key=f"default/{name}", node_name="host-0-0-0",
            device_ids=ids, coords=[TopologyCoord(0, 0, 0)],
        )
        return {"metadata": {
            "name": name, "namespace": "default",
            "annotations": {codec.ANNO_ALLOC: codec.encode_alloc(alloc)},
        }}

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    api = WatchApi()
    with TpuDeviceManager(cfg, host="host-0-0-0") as device, \
            DevicePluginServer(cfg, device) as server:
        w = apisrv.AllocIntentWatcher(api, "host-0-0-0", server,
                                      poll_seconds=0.05)
        assert w._use_watch
        w.start()
        try:
            api.events.put(("ADDED", pod_with_alloc("w0", ["tpu-2"])))
            deadline = _time.monotonic() + 5
            while (server.intents.snapshot().get("default/w0") != ["tpu-2"]
                   and _time.monotonic() < deadline):
                _time.sleep(0.01)
            assert server.intents.snapshot()["default/w0"] == ["tpu-2"]

            api.events.put(("DELETED", pod_with_alloc("w0", ["tpu-2"])))
            deadline = _time.monotonic() + 5
            while (server.intents.snapshot() and
                   _time.monotonic() < deadline):
                _time.sleep(0.01)
            assert server.intents.snapshot() == {}

            # stream close -> resync (list_pods) + reconnect
            api.pods = [pod_with_alloc("w1", ["tpu-3"])]
            api.events.put(None)
            deadline = _time.monotonic() + 5
            while (server.intents.snapshot().get("default/w1") != ["tpu-3"]
                   and _time.monotonic() < deadline):
                _time.sleep(0.01)
            assert server.intents.snapshot()["default/w1"] == ["tpu-3"]
            assert api.connects >= 2
            assert w.watch_events == 2
        finally:
            api.events.put(None)  # unblock the generator for stop()
            w.stop()


def test_watch_event_semantics(tmp_path):
    """Watch events must not resurrect consumed intents (a running pod's
    lifetime alloc annotation rides every MODIFIED/replay), and DELETED
    kills the intent even when the final object's annotation is corrupt."""
    from types import SimpleNamespace

    from tpukube.core.types import AllocResult, TopologyCoord
    from tpukube.plugin.server import AllocIntentCache

    class Api:  # just enough for __init__'s watch detection
        def watch_pods(self, *a, **k):
            return iter(())

        def list_pods(self, node_name=None):
            return []

    intents = AllocIntentCache()
    server = SimpleNamespace(intents=intents)
    w = apisrv.AllocIntentWatcher(Api(), "host-0-0-0", server,
                                  poll_seconds=999)
    assert w._use_watch

    def pod(name, ids, annotation=True):
        alloc = AllocResult(
            pod_key=f"default/{name}", node_name="host-0-0-0",
            device_ids=ids, coords=[TopologyCoord(0, 0, 0)],
        )
        annos = ({codec.ANNO_ALLOC: codec.encode_alloc(alloc)}
                 if annotation else {codec.ANNO_ALLOC: "{corrupt"})
        return {"metadata": {"name": name, "namespace": "default",
                             "annotations": annos}}

    w._apply_watch_event("ADDED", pod("a", ["tpu-0"]))
    assert intents.snapshot() == {"default/a": ["tpu-0"]}

    # the kubelet allocates exactly the plan: consumed + satisfied
    assert intents.consume(["tpu-0"]) == ("default/a", ["tpu-0"], False)
    # the pod's later MODIFIED event replays the same annotation: the
    # consumed intent must NOT come back
    w._apply_watch_event("MODIFIED", pod("a", ["tpu-0"]))
    assert intents.snapshot() == {}

    # DELETED with a CORRUPT annotation still kills the intent by key
    w._apply_watch_event("ADDED", pod("b", ["tpu-1"]))
    assert intents.snapshot() == {"default/b": ["tpu-1"]}
    w._apply_watch_event("DELETED", pod("b", ["tpu-1"], annotation=False))
    assert intents.snapshot() == {}


def test_watch_stop_interrupts_blocked_stream():
    """stop() must not hang behind a quiet watch: closing the stream
    unblocks the reader and the thread exits promptly."""
    import http.server
    import time as _time
    from types import SimpleNamespace

    from tpukube.plugin.server import AllocIntentCache

    connected = threading.Event()

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            if "watch=1" in self.path:
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                self.wfile.flush()
                connected.set()
                _time.sleep(30)  # a quiet node: no events
            else:
                body = json.dumps({"items": [], "metadata": {}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        api = apisrv.RestApiServer(
            base_url=f"http://127.0.0.1:{httpd.server_address[1]}",
            token="t",
        )
        w = apisrv.AllocIntentWatcher(
            api, "n0", SimpleNamespace(intents=AllocIntentCache()),
            poll_seconds=0.1,
        )
        w.start()
        assert connected.wait(timeout=10), "watch never connected"
        t0 = _time.monotonic()
        w.stop()
        assert _time.monotonic() - t0 < 5, "stop() hung behind the stream"
        assert w._thread is None
    finally:
        httpd.shutdown()


def test_health_transition_reannotates_node(tmp_path):
    """SURVEY §4.4 full circle: a chip fault re-emits the node-topology
    annotation (HealthWatcher's on_transition hook, as the daemon wires
    it) and the syncer PATCHes it onto the Node — so the EXTENDER, not
    just the kubelet, stops placing on the dead chip."""
    from tpukube.device import TpuDeviceManager
    from tpukube.plugin import DevicePluginServer
    from tpukube.plugin.server import HealthWatcher
    from tpukube.sched.extender import Extender

    cfg = _node_cfg(tmp_path, dims="2,2,1")
    api = apisrv.FakeApiServer()
    anno_file = tmp_path / "annotation.json"

    with TpuDeviceManager(cfg, host="host-0-0-0") as device, \
            DevicePluginServer(cfg, device) as server:

        def write_annotation():
            anno = codec.annotate_node(device.node_info(), device.mesh)
            anno_file.write_text(json.dumps(anno) + "\n")

        write_annotation()
        watcher = HealthWatcher(device, server, poll_seconds=999,
                                on_transition=write_annotation)
        watcher._last = device.health_snapshot()
        syncer = apisrv.NodeAnnotationSyncer(
            api, "host-0-0-0", str(anno_file), poll_seconds=999
        )
        assert syncer.check_once() is True  # initial topology applied

        device.inject_fault(0)              # chip 0 dies
        assert watcher.check_once() is True
        assert syncer.check_once() is True  # re-annotation flows

        ext = Extender(cfg)
        pod = {
            "metadata": {"name": "p0", "namespace": "default", "uid": "u",
                         "annotations": {}},
            "spec": {"containers": [{
                "name": "m",
                "resources": {"requests": {cfg.resource_tpu: "4"}},
            }]},
        }
        out = ext.handle("filter", {
            "Pod": pod, "Nodes": {"Items": api.node_objects()},
        })
        # 4 chips requested, only 3 healthy: the extender knows
        assert out["NodeNames"] == []
        assert "host-0-0-0" in out["FailedNodes"]

        device.inject_fault(0, healthy=True)  # recovery flows too
        assert watcher.check_once() is True
        assert syncer.check_once() is True
        out = ext.handle("filter", {
            "Pod": pod, "Nodes": {"Items": api.node_objects()},
        })
        assert out["NodeNames"] == ["host-0-0-0"]

        # an ICI link fault (all chips healthy) must re-annotate too:
        # badLinks is the extender's gang-placement input
        device.inject_link_fault((0, 0, 0), (1, 0, 0))
        assert watcher.check_once() is True
        assert syncer.check_once() is True
        topo = json.loads(
            api.get_node_annotations("host-0-0-0")[codec.ANNO_NODE_TOPOLOGY]
        )
        assert topo["badLinks"] == [[[0, 0, 0], [1, 0, 0]]]
        assert watcher.check_once() is False  # steady state: no re-emit


def test_node_refresh_loop_feeds_namescapable_cache():
    """nodeCacheCapable closes the topology loop through the apiserver:
    webhooks carry names only, so a health fault reaches the extender via
    NodeTopologyRefreshLoop's recorded upsert_node decisions — and the
    capture (names-mode webhooks + refreshes) replays deterministically."""
    from tpukube import trace as trace_mod
    from tpukube.core.config import load_config as _load
    from tpukube.core.types import ChipInfo, NodeInfo
    from tpukube.sched.extender import Extender

    cfg = _load(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    mesh = cfg.sim_mesh()
    chips = [
        ChipInfo(chip_id=f"c{i}", index=i, coord=c,
                 hbm_bytes=cfg.hbm_bytes_per_chip, num_cores=2)
        for i, c in enumerate(mesh.coords_of_host("host-0-0-0"))
    ]
    info = NodeInfo(name="host-0-0-0", chips=chips, slice_id=cfg.slice_id)
    api = apisrv.FakeApiServer()
    api.patch_node_annotations("host-0-0-0",
                               codec.annotate_node(info, mesh))

    ext = Extender(cfg)
    loop = apisrv.NodeTopologyRefreshLoop(ext, api, poll_seconds=999)
    assert loop.check_once() is True   # initial topology applied
    assert loop.check_once() is False  # unchanged: no re-apply
    assert loop.refreshed == 1

    pod = {
        "metadata": {"name": "p", "namespace": "default", "uid": "u",
                     "annotations": {}},
        "spec": {"containers": [{
            "name": "m",
            "resources": {"requests": {cfg.resource_tpu: "4"}},
        }]},
    }
    out = ext.handle("filter", {"Pod": pod,
                                "NodeNames": ["host-0-0-0"]})
    assert out["NodeNames"] == ["host-0-0-0"]

    # the node agent's re-annotation lands on the Node (syncer's PATCH);
    # the refresh loop folds it into the names-mode cache
    from tpukube.core.types import Health
    chips[0].health = Health.UNHEALTHY
    api.patch_node_annotations("host-0-0-0",
                               codec.annotate_node(info, mesh))
    assert loop.check_once() is True
    out = ext.handle("filter", {"Pod": dict(pod),
                                "NodeNames": ["host-0-0-0"]})
    assert out["NodeNames"] == []  # 4 asked, 3 healthy: extender knows

    # the whole capture — names-mode webhooks interleaved with
    # upsert_node refreshes — replays clean on a fresh extender
    assert ext.trace is not None
    divergences = trace_mod.replay(ext.trace.events(), config=cfg)
    assert divergences == []


def test_node_refresh_watch_mode_applies_fault_within_event():
    """Watch-mode NodeTopologyRefreshLoop (the node informer): a health
    re-annotation PATCHed onto the Node reaches the extender's cache via
    the watch stream — including one landing in the list->watch gap —
    without a single poll."""
    import time as _time

    from tpukube.core.config import load_config as _load
    from tpukube.core.types import ChipInfo, Health, NodeInfo
    from tpukube.sched.extender import Extender

    cfg = _load(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    mesh = cfg.sim_mesh()
    chips = [
        ChipInfo(chip_id=f"c{i}", index=i, coord=c,
                 hbm_bytes=cfg.hbm_bytes_per_chip, num_cores=2)
        for i, c in enumerate(mesh.coords_of_host("host-0-0-0"))
    ]
    info = NodeInfo(name="host-0-0-0", chips=chips, slice_id=cfg.slice_id)
    api = apisrv.FakeApiServer()
    api.patch_node_annotations("host-0-0-0",
                               codec.annotate_node(info, mesh))

    ext = Extender(cfg)
    loop = apisrv.NodeTopologyRefreshLoop(ext, api, poll_seconds=999)
    assert loop._use_watch
    loop.start()
    try:
        deadline = _time.monotonic() + 5
        while ext.state.node("host-0-0-0") is None \
                and _time.monotonic() < deadline:
            _time.sleep(0.01)
        view = ext.state.node("host-0-0-0")
        assert view is not None  # initial resync applied the topology

        # the node agent reports a chip fault; the WATCH delivers it
        chips[0].health = Health.UNHEALTHY
        api.patch_node_annotations("host-0-0-0",
                                   codec.annotate_node(info, mesh))
        deadline = _time.monotonic() + 5
        while not ext.state.unhealthy_coords(cfg.slice_id) \
                and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert ext.state.unhealthy_coords(cfg.slice_id)
        assert loop.refreshed == 2
    finally:
        loop.stop()


def test_rebuild_primes_refresh_loop():
    """round-4 advisor low: a restart's rebuild primes the refresh loop,
    so the first poll re-dispatches NOTHING the rebuild already applied —
    zero duplicate upsert_node decisions, an honest ``refreshed``
    counter; a real post-restart change still dispatches."""
    from tpukube.core.config import load_config as _load
    from tpukube.core.types import ChipInfo, Health, NodeInfo
    from tpukube.sched.extender import Extender

    cfg = _load(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    mesh = cfg.sim_mesh()
    chips = [
        ChipInfo(chip_id=f"c{i}", index=i, coord=c,
                 hbm_bytes=cfg.hbm_bytes_per_chip, num_cores=2)
        for i, c in enumerate(mesh.coords_of_host("host-0-0-0"))
    ]
    info = NodeInfo(name="host-0-0-0", chips=chips, slice_id=cfg.slice_id)
    api = apisrv.FakeApiServer()
    api.patch_node_annotations("host-0-0-0",
                               codec.annotate_node(info, mesh))

    ext = Extender(cfg)
    refresh = apisrv.NodeTopologyRefreshLoop(ext, api, poll_seconds=999)
    assert apisrv.rebuild_extender(ext, api, refresh=refresh) == 0
    events_after_rebuild = len(ext.trace.events())
    assert refresh.check_once() is False  # primed: nothing to re-apply
    assert refresh.refreshed == 0
    assert len(ext.trace.events()) == events_after_rebuild

    # a genuine post-restart change still flows through
    chips[0].health = Health.UNHEALTHY
    api.patch_node_annotations("host-0-0-0",
                               codec.annotate_node(info, mesh))
    assert refresh.check_once() is True
    assert refresh.refreshed == 1


def test_concurrent_binds_with_flaky_binder():
    """The out-of-lock bind effector under concurrency: interleaved slow
    and failing binder calls must never corrupt the ledger — every pod
    eventually binds (scheduler retries), every chip is held by exactly
    one pod, and the apiserver's nodeName agrees with the ledger."""
    import itertools
    import time as _time

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        api = apisrv.FakeApiServer()
        real_binder = apisrv.pod_binder(api)
        calls = itertools.count()

        def flaky_binder(alloc):
            n = next(calls)
            _time.sleep(0.001 * (n % 3))  # stagger interleavings
            if n % 3 == 0:
                raise apisrv.ApiServerError("transient apiserver blip")
            real_binder(alloc)

        c.extender.binder = flaky_binder
        errs = []

        def run(i):
            import copy

            pod = c.make_pod(f"p-{i}", tpu=1)
            # a DEEP copy into the apiserver: the harness mutates its own
            # pod dict at bind, and a shared reference would make the
            # ledger-vs-apiserver assertions below vacuously true
            api.upsert_pod(copy.deepcopy(pod))
            try:
                c.schedule(pod, retries=16)
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(f"p-{i}: {e}")

        ts = [threading.Thread(target=run, args=(i,)) for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs

        allocs = list(c.extender.state.allocations())
        assert len(allocs) == 16
        # no device id double-held on any node
        seen: dict[tuple, str] = {}
        for a in allocs:
            for did in a.device_ids:
                key = (a.node_name, did)
                assert key not in seen, (
                    f"{key} held by {seen[key]} AND {a.pod_key}"
                )
                seen[key] = a.pod_key
        # every pod bound THROUGH the apiserver channel exactly once
        binds = [e for e in api.patch_log if e[0] == "bind"]
        assert len(binds) == 16
        # the apiserver agrees with the ledger, pod by pod
        for a in allocs:
            ns, name = a.pod_key.split("/", 1)
            pod = api.get_pod(ns, name)
            assert pod["spec"]["nodeName"] == a.node_name
            persisted = codec.decode_alloc(
                pod["metadata"]["annotations"][codec.ANNO_ALLOC]
            )
            assert persisted.device_ids == a.device_ids
        assert c.utilization() == 1.0


def test_intent_watcher_watch_mode_over_fake_api(tmp_path):
    """The sim's apiserver speaks the watch protocol too: an intent
    arrives through a real bind event (the Pending upsert doesn't match
    the nodeName field selector; the Binding's MODIFIED does), DELETED
    drops it, and stop() unblocks a quiet watch through the handle."""
    import time as _time
    from types import SimpleNamespace

    from tpukube.core.types import AllocResult, TopologyCoord
    from tpukube.plugin.server import AllocIntentCache

    api = apisrv.FakeApiServer()
    server = SimpleNamespace(intents=AllocIntentCache())
    w = apisrv.AllocIntentWatcher(api, "host-0-0-0", server,
                                  poll_seconds=0.05)
    assert w._use_watch
    w.start()
    try:
        pod = {"metadata": {"name": "a", "namespace": "default",
                            "annotations": {}}, "spec": {}}
        api.upsert_pod(pod)  # Pending: field selector filters this out
        alloc = AllocResult(
            pod_key="default/a", node_name="host-0-0-0",
            device_ids=["tpu-2"], coords=[TopologyCoord(0, 0, 0)],
        )
        api.bind_pod("default", "a", "host-0-0-0",
                     {codec.ANNO_ALLOC: codec.encode_alloc(alloc)})
        deadline = _time.monotonic() + 5
        while (server.intents.snapshot().get("default/a") != ["tpu-2"]
               and _time.monotonic() < deadline):
            _time.sleep(0.01)
        assert server.intents.snapshot()["default/a"] == ["tpu-2"]

        api.delete_pod("default", "a")
        deadline = _time.monotonic() + 5
        while server.intents.snapshot() and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert server.intents.snapshot() == {}

        t0 = _time.monotonic()
        w.stop()
        assert _time.monotonic() - t0 < 4, "stop() hung behind the fake watch"
        w = None
    finally:
        if w is not None:
            w.stop()
    assert api._watch_queues == []  # subscription cleaned up

from tpukube.core.types import (
    RESOURCE_TPU,
    RESOURCE_VTPU,
    AllocResult,
    ContainerInfo,
    PodInfo,
    ResourceList,
    TopologyCoord,
    iter_pod_device_requests,
    make_device_id,
    parse_device_id,
)

import pytest


def test_device_id_roundtrip_whole():
    d = make_device_id(3)
    assert d == "tpu-3"
    assert parse_device_id(d) == (3, None)


def test_device_id_roundtrip_frac():
    d = make_device_id(7, (1, 4))
    assert d == "tpu-7-frac1of4"
    assert parse_device_id(d) == (7, (1, 4))


@pytest.mark.parametrize("bad", ["gpu-0", "tpu-", "tpu-1-frac", "tpu-1-frac1", "x"])
def test_device_id_rejects_junk(bad):
    with pytest.raises(ValueError):
        parse_device_id(bad)


def test_resource_list_arithmetic():
    cap = ResourceList({RESOURCE_TPU: 4})
    req = ResourceList({RESOURCE_TPU: 2})
    assert req.fits(cap)
    left = cap.minus(req)
    assert left[RESOURCE_TPU] == 2
    assert left.nonneg()
    assert not ResourceList({RESOURCE_TPU: 5}).fits(cap)
    assert ResourceList().fits(cap)  # empty request always fits
    assert cap.minus({RESOURCE_TPU: 5})[RESOURCE_TPU] == -1


def test_pod_requests_sum_containers():
    pod = PodInfo(
        name="p",
        containers=[
            ContainerInfo("a", ResourceList({RESOURCE_TPU: 1})),
            ContainerInfo("b", ResourceList({RESOURCE_TPU: 1, RESOURCE_VTPU: 2})),
        ],
    )
    req = pod.requests()
    assert req[RESOURCE_TPU] == 2 and req[RESOURCE_VTPU] == 2
    assert dict(iter_pod_device_requests(pod)) == {RESOURCE_TPU: 2, RESOURCE_VTPU: 2}
    assert pod.uid == "default/p"


def test_alloc_result_chip_indices():
    a = AllocResult(
        pod_key="default/p",
        node_name="host-0-0-0",
        device_ids=["tpu-0", "tpu-2-frac1of2"],
        coords=[TopologyCoord(0, 0, 0), TopologyCoord(1, 1, 0)],
    )
    assert a.chip_indices() == [0, 2]

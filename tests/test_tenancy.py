"""ISSUE 9: the multi-tenant serving plane.

Three load-bearing contracts:

  * OFF IS OFF — with ``tenancy_enabled`` false (the default) nothing
    tenant-related is constructed, no tenant series render, and every
    placement path is the pre-tenancy code; a NEUTRAL plane (enabled,
    one tenant, no quotas, no burn) must additionally change no
    placement — proven per-workload and across whole sim scenarios.
  * QUOTAS NEVER VIOLATE — the admission gate refuses (with a typed
    journal event) any placement that would push a tenant over its
    caps, under random arrival orders, and the DRF queue order keeps
    the dominant-share spread bounded.
  * REFUSALS ARE NEVER SILENT — every shed/denial increments a counter
    AND lands in the journal as TenantAdmissionShed/TenantQuotaDenied.
"""

from __future__ import annotations

import os
import random
from types import SimpleNamespace

import pytest

from tpukube.core.clock import FakeClock
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sched import kube
from tpukube.sim.harness import SimCluster
from tpukube.tenancy import BurnMonitor, TenantPlane, parse_quotas

TENANT_LABEL = "tpu.qiniu.com/tenant"

SMALL = {
    "TPUKUBE_SIM_MESH_DIMS": "4,4,2",
    "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
}


def _cfg(tenancy: bool = True, batch: bool = False, quotas: str = "",
         **extra: str):
    env = dict(SMALL)
    env.update(extra)
    if tenancy:
        env["TPUKUBE_TENANCY_ENABLED"] = "1"
    if quotas:
        env["TPUKUBE_TENANCY_QUOTAS"] = quotas
    if batch:
        env["TPUKUBE_BATCH_ENABLED"] = "1"
    return load_config(env=env)


def _placement(alloc):
    return (alloc.node_name, tuple(sorted(alloc.device_ids)),
            tuple(sorted(tuple(c) for c in alloc.coords)))


# -- quota spec / config -----------------------------------------------------

def test_parse_quotas():
    q = parse_quotas("teamA=chips:16,hbm:0.25;teamB=chips:8")
    assert q["teamA"].chips == 16 and q["teamA"].hbm_fraction == 0.25
    assert q["teamB"].chips == 8 and q["teamB"].hbm_fraction is None
    assert parse_quotas("") == {}
    assert parse_quotas(" ; ") == {}


@pytest.mark.parametrize("bad", [
    "noequals", "a=", "a=chips", "a=chips:x", "a=chips:0",
    "a=hbm:1.5", "a=hbm:0", "a=cores:2", "a=chips:1;a=chips:2",
])
def test_parse_quotas_rejects(bad):
    with pytest.raises(ValueError):
        parse_quotas(bad)


def test_config_validates_quota_spec_and_defaults_off():
    cfg = load_config(env={})
    assert cfg.tenancy_enabled is False
    from tpukube.sched.extender import Extender

    assert Extender(cfg).tenants is None
    with pytest.raises(ValueError, match="tenancy_quotas"):
        load_config(env={"TPUKUBE_TENANCY_ENABLED": "1",
                         "TPUKUBE_TENANCY_QUOTAS": "a=chips:-3"})
    with pytest.raises(ValueError, match="tenancy_burn_threshold"):
        load_config(env={"TPUKUBE_TENANCY_BURN_THRESHOLD": "-1"})
    # quotas without the plane would be silently unenforced: refuse
    with pytest.raises(ValueError, match="tenancy_enabled"):
        load_config(env={"TPUKUBE_TENANCY_QUOTAS": "a=chips:4"})


# -- tenant identity + ledger ------------------------------------------------

def test_tenant_from_label_and_default():
    cfg = _cfg()
    from tpukube.sched.extender import Extender

    ext = Extender(cfg)
    labeled = kube.pod_from_k8s({
        "metadata": {"name": "p", "labels": {TENANT_LABEL: "teamA"}},
        "spec": {},
    })
    bare = kube.pod_from_k8s({"metadata": {"name": "q"}, "spec": {}})
    assert ext.tenants.tenant_of(labeled) == "teamA"
    assert ext.tenants.tenant_of(bare) == "default"


def test_ledger_usage_from_allocations_and_reservations():
    cfg = _cfg(quotas="a=chips:20")
    with SimCluster(cfg, in_process=True) as c:
        ext = c.extender
        for i in range(3):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        c.schedule(c.make_pod("b-0", tpu=2, labels={TENANT_LABEL: "b"}))
        c.schedule(c.make_pod("bare", tpu=1))
        # a reserving (uncommitted) gang charges its tenant too
        g = PodGroup("g", min_member=4)
        c.make_pod("g-0", tpu=1, priority=5, group=g,
                   labels={TENANT_LABEL: "a"})
        args, _ = c._extender_node_args()
        c._post("/filter", {"Pod": c.pods["default/g-0"], **args})
        snap = ext.tenants.ledger.usage()
        assert snap.usage["a"].chips == 3 + 4  # allocs + reservation
        assert snap.usage["b"].chips == 2
        assert snap.usage["default"].chips == 1
        assert snap.capacity_chips == 32
        assert snap.usage["a"].hbm_bytes > 0
        assert 0 < snap.dominant_share("b") < snap.dominant_share("a")
        # burst accounting: priority-0 non-gang chips only
        assert snap.usage["a"].burst_chips == 3
        # the alloc annotation carries the tenant (restart channel)
        alloc = ext.state.allocation("default/a-0")
        assert alloc.env["TPU_KUBE_TENANT"] == "a"


def test_vtpu_shares_count_fractionally():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,1,1",
        "TPUKUBE_SHARES_PER_CHIP": "2",
        "TPUKUBE_TENANCY_ENABLED": "1",
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"}, vtpu_shares=2,
                    in_process=True) as c:
        c.schedule(c.make_pod("i-0", vtpu=1, labels={TENANT_LABEL: "a"}))
        snap = c.extender.tenants.ledger.usage()
        assert snap.usage["a"].chips == pytest.approx(0.5)
        assert snap.vtpu_shares == 2


def test_tenant_attribution_survives_restart():
    cfg = _cfg()
    with SimCluster(cfg) as c:
        g = PodGroup("phoenix", min_member=2)
        for i in range(2):
            c.schedule(c.make_pod(f"p-{i}", tpu=1, priority=5, group=g,
                                  labels={TENANT_LABEL: "teamX"}))
        assert c.extender.gang.snapshot()[0].tenant == "teamX"
        c.crash_extender()
        c.restart_extender()
        res = c.extender.gang.snapshot()
        assert res and res[0].tenant == "teamX"
        snap = c.extender.tenants.ledger.usage()
        assert snap.usage["teamX"].chips == 2


# -- admission: quotas -------------------------------------------------------

def test_quota_denial_is_journaled_and_exact():
    cfg = _cfg(quotas="a=chips:2")
    with SimCluster(cfg, in_process=True) as c:
        for i in range(2):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        with pytest.raises(RuntimeError, match="quota"):
            c.schedule(c.make_pod("a-2", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        plane = c.extender.tenants
        assert plane.quota_denials == {"a": 1}
        reasons = c.extender.events.counts_by_reason()
        assert reasons.get("TenantQuotaDenied", 0) == 1
        # an unquota'd tenant is untouched
        c.schedule(c.make_pod("b-0", tpu=1, labels={TENANT_LABEL: "b"}))


def test_gang_charged_once_members_ride_the_reservation():
    # first member charges the WHOLE gang; quota must cover it up front
    cfg = _cfg(quotas="a=chips:4")
    with SimCluster(cfg, in_process=True) as c:
        g = PodGroup("fits", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"f-{i}", tpu=1, priority=5, group=g,
                                  labels={TENANT_LABEL: "a"}))
        assert c.extender.tenants.quota_denials == {}
        g2 = PodGroup("toobig", min_member=2)
        with pytest.raises(RuntimeError, match="quota"):
            c.schedule(c.make_pod("t-0", tpu=1, priority=5, group=g2,
                                  labels={TENANT_LABEL: "a"}))


def test_overflow_gang_replicas_are_quota_charged():
    """Replicas beyond min_member of a full gang schedule as NORMAL
    pods on fresh chips (gang.assignable False) — they must be charged
    against the quota like any burst, not ride the reservation's
    exemption."""
    cfg = _cfg(quotas="a=chips:4")
    with SimCluster(cfg, in_process=True) as c:
        g = PodGroup("full", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"m-{i}", tpu=1, priority=5, group=g,
                                  labels={TENANT_LABEL: "a"}))
        # the 5th replica would take a 5th chip: quota refuses it
        with pytest.raises(RuntimeError, match="quota"):
            c.schedule(c.make_pod("m-4", tpu=1, priority=5, group=g,
                                  labels={TENANT_LABEL: "a"}))
        assert c.extender.tenants.quota_denials == {"a": 1}


# -- burn monitor + SLO shedding ---------------------------------------------

def _gang_hist():
    from tpukube.obs.registry import Histogram

    return Histogram("gang_schedule_latency_seconds", bucket_only=True)


def test_burn_monitor_windows():
    clock = FakeClock()
    hist = _gang_hist()
    mon = BurnMonitor(clock, threshold=14.4, window=60.0)
    mon.attach_default_slos({"gang_schedule_latency_seconds": hist})
    assert mon.page_burning() is None  # no traffic, no burn
    hist.observe(0.1)
    clock.advance(1.0)  # the verdict is memoized per clock instant
    assert mon.page_burning() is None  # within SLO
    hist.observe(5.0)  # blows the 2.5s objective
    clock.advance(1.0)
    assert "gang-schedule-latency" in mon.page_burning()
    assert mon.last_page_burning() is True
    # the bad sample ages out of the sliding window
    clock.advance(61.0)
    mon.evaluate()  # slides B
    clock.advance(61.0)
    mon.evaluate()  # slides A past the sample
    clock.advance(1.0)
    assert mon.page_burning() is None
    assert mon.last_page_burning() is False


def test_burn_monitor_memoizes_per_clock_instant():
    clock = FakeClock()
    hist = _gang_hist()
    mon = BurnMonitor(clock, threshold=14.4, window=60.0)
    mon.attach_default_slos({"gang_schedule_latency_seconds": hist})
    hist.observe(5.0)
    clock.advance(1.0)
    assert mon.page_burning() is not None
    evals_a = dict(mon.last_burns)
    # a whole drain's admissions at one tick share the one verdict
    # (no re-scan) — the next tick re-evaluates
    assert mon.page_burning() is not None
    assert mon.last_burns == evals_a


def test_burn_monitor_resets_after_idle_gap():
    """Admissions drive evaluations, so an overnight-idle plane must
    not judge the morning's first burst against a giant stale window
    (a slow commit from last night would shed healthy traffic)."""
    clock = FakeClock()
    hist = _gang_hist()
    mon = BurnMonitor(clock, threshold=14.4, window=60.0)
    mon.attach_default_slos({"gang_schedule_latency_seconds": hist})
    hist.observe(0.1)
    clock.advance(1.0)
    assert mon.page_burning() is None
    hist.observe(5.0)  # the overnight bad sample
    clock.advance(10_000.0)  # idle far past two windows
    assert mon.page_burning() is None  # reset, not a stale-window shed
    # a burn that is STILL happening re-crosses within one window
    hist.observe(5.0)
    clock.advance(30.0)
    assert mon.page_burning() is not None


def test_single_burst_tenant_never_sheds():
    """With one bursting tenant its share IS the population mean, so
    fairness-based shedding has no target — by design (quotas are the
    single-tenant overload knob), and it is what keeps a neutral
    single-tenant plane placement-identical to tenancy off."""
    cfg = _cfg()
    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        ext = c.extender
        for i in range(6):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        ext.gang.commit_hist.observe(5.0)  # page burn
        clock.advance(1.0)
        c.schedule(c.make_pod("a-more", tpu=1,
                              labels={TENANT_LABEL: "a"}))
        assert ext.tenants.counter_snapshot()[0] == {}


def test_slo_shed_targets_overshare_low_priority_bursts_only():
    cfg = _cfg()
    clock = FakeClock()
    with SimCluster(cfg, clock=clock, in_process=True) as c:
        ext = c.extender
        plane = ext.tenants
        # tenant a hogs the burst plane, b sips
        for i in range(6):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        c.schedule(c.make_pod("b-0", tpu=1, labels={TENANT_LABEL: "b"}))
        # no burn -> nobody sheds
        c.schedule(c.make_pod("a-ok", tpu=1, labels={TENANT_LABEL: "a"}))
        # a gang commit blows the 2.5s SLO: page burn (advance past
        # the per-tick verdict memo)
        ext.gang.commit_hist.observe(5.0)
        clock.advance(1.0)
        with pytest.raises(RuntimeError, match="admission shed"):
            c.schedule(c.make_pod("a-shed", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        # under-share tenant still admitted during the burn
        c.schedule(c.make_pod("b-1", tpu=1, labels={TENANT_LABEL: "b"}))
        # higher-priority work of the over-share tenant is not shed
        c.schedule(c.make_pod("a-prio", tpu=1, priority=10,
                              labels={TENANT_LABEL: "a"}))
        # ...and neither are gang members (training never sheds)
        g = PodGroup("train", min_member=2)
        for i in range(2):
            c.schedule(c.make_pod(f"g-{i}", tpu=1, priority=5, group=g,
                                  labels={TENANT_LABEL: "a"}))
        sheds, _ = plane.counter_snapshot()
        assert sheds == {"a": 1}
        reasons = ext.events.counts_by_reason()
        assert reasons.get("TenantAdmissionShed", 0) == 1


# -- DRF ordering: property test ---------------------------------------------

def _drive_batch(c, pods):
    """Admit + plan + bind a pod list through the batch planner,
    tolerating unschedulable leftovers. Returns placed count."""
    ext = c.extender
    c._sync_nodes()
    for obj in pods:
        ext.admit(kube.pod_from_k8s(obj))
    ext.plan_pending()
    placed = 0
    for obj in pods:
        meta = obj["metadata"]
        node = ext.planned_node(f"{meta['namespace']}/{meta['name']}")
        if node is None:
            continue
        bres = c._post("/bind", {
            "PodName": meta["name"], "PodNamespace": meta["namespace"],
            "PodUID": meta["uid"], "Node": node,
        })
        if not bres.get("Error"):
            placed += 1
    return placed


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_drf_never_exceeds_quota_and_bounds_spread(seed):
    """Property: under random arrival orders on a saturated mesh, the
    allocator never exceeds any tenant quota and the dominant-share
    spread stays bounded (max/min <= 2.0)."""
    tenants = ["a", "b", "c"]
    cfg = _cfg(batch=True, quotas=";".join(
        f"{t}=chips:12" for t in tenants
    ))
    rng = random.Random(seed)
    with SimCluster(cfg, in_process=True) as c:  # 32 chips
        pods = []
        for t in tenants:
            for i in range(14):  # oversubscribed: 42 offered for 32
                pods.append(c.make_pod(f"{t}-{i}", tpu=1,
                                       labels={TENANT_LABEL: t}))
        rng.shuffle(pods)
        placed = _drive_batch(c, pods)
        snap = c.extender.tenants.ledger.usage()
        chips = {t: snap.usage.get(t).chips if t in snap.usage else 0.0
                 for t in tenants}
        for t in tenants:
            assert chips[t] <= 12 + 1e-9, (t, chips)
        assert placed == 32  # full plane despite quotas
        ratio = max(chips.values()) / min(chips.values())
        assert ratio <= 2.0, (chips, ratio)


def test_drf_order_interleaves_tenants_per_pick():
    """The queue order itself: all of tenant a enqueued before any of
    b must still interleave a/b in the drained order."""
    cfg = _cfg(batch=True)
    with SimCluster(cfg, in_process=True) as c:
        ext = c.extender
        entries = []
        for seq, (t, n) in enumerate(
            [("a", f"a-{i}") for i in range(4)]
            + [("b", f"b-{i}") for i in range(4)]
        ):
            pod = kube.pod_from_k8s(c.make_pod(
                n, tpu=1, labels={TENANT_LABEL: t}))
            entries.append((pod, seq, None))
        ordered = ext.tenants.drf_order(entries)
        tenants_in_order = [
            e[0].labels[TENANT_LABEL] for e in ordered
        ]
        assert tenants_in_order == ["a", "b", "a", "b",
                                    "a", "b", "a", "b"]


# -- tenant-aware preemption victim choice -----------------------------------

def test_preemption_prefers_overshare_victims_at_equal_cost():
    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import TopologyCoord
    from tpukube.sched import policy

    mesh = MeshSpec(dims=(4, 1, 1), host_block=(1, 1, 1))
    wa = policy.Workload(
        id="pa", priority=1, cost=1,
        coords=frozenset({TopologyCoord(0, 0, 0), TopologyCoord(1, 0, 0)}),
        pod_keys=("default/pa",), tenant="a",
    )
    wb = policy.Workload(
        id="pb", priority=1, cost=1,
        coords=frozenset({TopologyCoord(2, 0, 0), TopologyCoord(3, 0, 0)}),
        pod_keys=("default/pb",), tenant="b",
    )
    base = policy.find_preemption_plan(
        [wa, wb], mesh, set(), 2, None, 10
    )
    assert [w.id for w in base.victims] == ["pa"]  # legacy tie-break
    biased = policy.find_preemption_plan(
        [wa, wb], mesh, set(), 2, None, 10, overshare={"b": 0.5}
    )
    assert [w.id for w in biased.victims] == ["pb"]
    # an all-zero bias map changes nothing (the tenancy-off shape)
    neutral = policy.find_preemption_plan(
        [wa, wb], mesh, set(), 2, None, 10, overshare={}
    )
    assert [w.id for w in neutral.victims] == ["pa"]
    assert (neutral.cost_priority_sum, neutral.victim_count) == (
        base.cost_priority_sum, base.victim_count
    )


# -- parity: off is off, neutral changes nothing -----------------------------

def _mixed_workload_placements(cfg) -> dict:
    """A placement-heavy workload: bursts, a preempting gang, backfill.
    Returns pod -> placement."""
    out = {}
    with SimCluster(cfg, in_process=True) as c:
        for i in range(12):
            _, alloc = c.schedule(c.make_pod(f"burst-{i}", tpu=1))
            out[f"burst-{i}"] = _placement(alloc)
        g = PodGroup("train", min_member=16)
        for i in range(16):
            _, alloc = c.schedule(
                c.make_pod(f"t-{i}", tpu=1, priority=50, group=g))
            out[f"t-{i}"] = _placement(alloc)
        fill = 0
        while True:
            try:
                _, alloc = c.schedule(c.make_pod(f"fill-{fill}", tpu=1))
            except RuntimeError:
                break
            out[f"fill-{fill}"] = _placement(alloc)
            fill += 1
    return out


@pytest.mark.parametrize("batch", [False, True])
def test_neutral_plane_placements_bit_identical(batch):
    """tenancy on with one tenant and no quotas = the legacy
    placements, webhook path and batch path alike (incl. preemption)."""
    legacy = _mixed_workload_placements(_cfg(tenancy=False, batch=batch))
    neutral = _mixed_workload_placements(_cfg(tenancy=True, batch=batch))
    assert legacy == neutral


def test_tenancy_off_renders_no_tenant_series_or_env():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    with SimCluster(_cfg(tenancy=False), in_process=True) as c:
        _, alloc = c.schedule(c.make_pod("p", tpu=1))
        assert "TPU_KUBE_TENANT" not in alloc.env
        text = render_extender_metrics(c.extender)
        assert "tpukube_tenant" not in text and "tenancy" not in text
        assert extender_statusz(c.extender)["tenants"] == {
            "enabled": False
        }


def test_tenancy_on_renders_tenant_series_and_statusz():
    from tpukube.metrics import render_extender_metrics
    from tpukube.obs.statusz import extender_statusz

    with SimCluster(_cfg(quotas="a=chips:4,hbm:0.5"),
                    in_process=True) as c:
        c.schedule(c.make_pod("p", tpu=1, labels={TENANT_LABEL: "a"}))
        text = render_extender_metrics(c.extender)
        assert 'tpukube_tenant_chips_used{tenant="a"} 1' in text
        assert 'tpukube_tenant_quota_chips{tenant="a"} 4' in text
        assert "tpukube_tenancy_shedding 0" in text
        doc = extender_statusz(c.extender)["tenants"]
        assert doc["enabled"] and doc["tenants"]["a"]["chips_used"] == 1
        # the exposition stays lint-clean with the new families on
        from tpukube.obs.slo import validate_exposition

        assert validate_exposition(text) == []


#: per-scenario placement-relevant result keys (timing excluded) — the
#: same table shape test_cycle.py uses for batch parity
SCENARIO_KEYS = {
    1: ("node", "devices", "env_keys", "utilization_percent"),
    2: ("placements", "utilization_percent"),
    3: ("pods", "shared_one_chip"),
    4: ("gang_box", "contiguous", "utilization_percent"),
    5: ("value", "vs_baseline", "preemptions", "pods_placed"),
    6: ("value", "waves", "wave_size", "full_utilization_percent",
        "util_min_after_refill_percent", "lifecycle_releases"),
}


def _scenario_result(n: int, tenancy: bool, keys):
    from tpukube.sim import scenarios

    old = os.environ.pop("TPUKUBE_TENANCY_ENABLED", None)
    try:
        if tenancy:
            os.environ["TPUKUBE_TENANCY_ENABLED"] = "1"
        r = scenarios.run(n)
    finally:
        os.environ.pop("TPUKUBE_TENANCY_ENABLED", None)
        if old is not None:
            os.environ["TPUKUBE_TENANCY_ENABLED"] = old
    return {k: r[k] for k in keys}


@pytest.mark.parametrize("scenario", sorted(SCENARIO_KEYS))
def test_scenario_placements_bit_identical_with_neutral_tenancy(scenario):
    keys = SCENARIO_KEYS[scenario]
    legacy = _scenario_result(scenario, False, keys)
    neutral = _scenario_result(scenario, True, keys)
    assert legacy == neutral, f"scenario {scenario} diverged"


# -- the informer admission feed (ROADMAP follow-up) -------------------------

def _pending_pod(name: str, tpu: int = 1, bound: bool = False,
                 phase: str = "", plain: bool = False):
    requests = {} if plain else {"qiniu.com/tpu": str(tpu)}
    pod = {
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}"},
        "spec": {"containers": [
            {"name": "main", "resources": {"requests": requests}}
        ]},
    }
    if bound:
        pod["spec"]["nodeName"] = "host-0-0-0"
    if phase:
        pod["status"] = {"phase": phase}
    return pod


def test_pod_admission_feed_routes_pending_pods_into_the_queue():
    from tpukube.apiserver import PodAdmissionFeed
    from tpukube.sched.extender import Extender

    ext = Extender(_cfg(tenancy=False, batch=True))
    api = SimpleNamespace(list_pods=lambda node=None: [
        _pending_pod("listed"), _pending_pod("bound", bound=True),
    ])
    feed = PodAdmissionFeed(ext, api, use_watch=False)
    assert ext.cycle.queue_depth() == 0
    feed._apply_watch_event("ADDED", _pending_pod("p1"))
    assert ext.cycle.queue_depth() == 1
    # idempotent per key; MODIFIED refreshes, never duplicates
    feed._apply_watch_event("MODIFIED", _pending_pod("p1"))
    assert ext.cycle.queue_depth() == 1
    # bound / terminal / non-TPU / malformed pods never enter
    feed._apply_watch_event("ADDED", _pending_pod("b", bound=True))
    feed._apply_watch_event("ADDED", _pending_pod("done",
                                                  phase="Succeeded"))
    feed._apply_watch_event("ADDED", _pending_pod("cpu", plain=True))
    feed._apply_watch_event("ADDED", {"metadata": {}})
    feed._apply_watch_event("DELETED", _pending_pod("p1"))
    assert ext.cycle.queue_depth() == 1
    # the list-resync half admits pending pods too
    assert feed.check_once() is True
    assert ext.cycle.queue_depth() == 2
    assert feed.admitted == 3


def test_pod_admission_feed_is_noop_without_batching():
    from tpukube.apiserver import PodAdmissionFeed
    from tpukube.sched.extender import Extender

    ext = Extender(_cfg(tenancy=False, batch=False))
    feed = PodAdmissionFeed(ext, SimpleNamespace(), use_watch=False)
    feed._apply_watch_event("ADDED", _pending_pod("p1"))
    assert ext.cycle is None  # nothing to enqueue into, nothing broke


def test_informer_fed_pods_plan_and_bind_end_to_end():
    """Regression for the ROADMAP follow-up: a pod arriving through the
    informer feed (no /filter webhook) is planned by the next cycle and
    its /bind consumes the assumed allocation."""
    from tpukube.apiserver import PodAdmissionFeed

    with SimCluster(_cfg(tenancy=False, batch=True),
                    in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        pod_obj = c.make_pod("fed", tpu=1)
        feed = PodAdmissionFeed(
            ext, SimpleNamespace(list_pods=lambda node=None: []),
            use_watch=False,
        )
        feed._apply_watch_event("ADDED", pod_obj)
        assert ext.cycle.queue_depth() == 1
        assert ext.plan_pending() == 1
        node = ext.planned_node("default/fed")
        assert node is not None
        bres = c._post("/bind", {
            "PodName": "fed", "PodNamespace": "default",
            "PodUID": pod_obj["metadata"]["uid"], "Node": node,
        })
        assert not bres.get("Error")
        assert ext.state.allocation("default/fed") is not None


def test_informer_redelivery_never_replans_an_assumed_allocation():
    """Regression: a MODIFIED event (or list resync) for a pod whose
    batch plan already ASSUMED an allocation must not re-enqueue it —
    a replan would double-commit its chips and orphan the original
    allocation from the plan table."""
    from tpukube.apiserver import PodAdmissionFeed

    with SimCluster(_cfg(tenancy=False, batch=True),
                    in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        pod_obj = c.make_pod("redeliver", tpu=1)
        feed = PodAdmissionFeed(
            ext, SimpleNamespace(list_pods=lambda node=None: [pod_obj]),
            use_watch=False,
        )
        feed._apply_watch_event("ADDED", pod_obj)
        assert ext.plan_pending() == 1  # planned + assumed
        alloc = ext.state.allocation("default/redeliver")
        assert alloc is not None
        # the informer re-delivers the still-pending pod
        feed._apply_watch_event("MODIFIED", pod_obj)
        feed.check_once()  # list resync re-delivers it too
        assert ext.cycle.queue_depth() == 0
        assert ext.plan_pending() == 0  # nothing replanned
        assert ext.state.allocation("default/redeliver") is alloc
        # the eventual /bind still consumes the one assumed allocation
        node = ext.planned_node("default/redeliver")
        bres = c._post("/bind", {
            "PodName": "redeliver", "PodNamespace": "default",
            "PodUID": pod_obj["metadata"]["uid"], "Node": node,
        })
        assert not bres.get("Error")


def test_shed_pod_recovers_after_burn_subsides():
    """Regression: a shed refusal is TIME-dependent, so it must never
    be served from the plan cache or block re-admission — once the
    burn window slides past the bad sample, the same pod (same uid,
    same epochs) schedules."""
    clock = FakeClock()
    with SimCluster(_cfg(batch=True), clock=clock,
                    in_process=True) as c:
        ext = c.extender
        c._sync_nodes()
        # two tenants so shedding has an over-share target
        for i in range(4):
            c.schedule(c.make_pod(f"a-{i}", tpu=1,
                                  labels={TENANT_LABEL: "a"}))
        c.schedule(c.make_pod("b-0", tpu=1, labels={TENANT_LABEL: "b"}))
        ext.gang.commit_hist.observe(5.0)  # page burn
        clock.advance(1.0)
        victim = c.make_pod("a-shed", tpu=1, labels={TENANT_LABEL: "a"})
        with pytest.raises(RuntimeError, match="admission shed"):
            c.schedule(victim)
        # the burn subsides with NO epoch movement (nothing scheduled)
        clock.advance(200.0)  # past two 60s windows: monitor resets
        node, alloc = c.schedule(victim)
        assert ext.state.allocation("default/a-shed") is not None
        # informer path recovers too: admit() re-runs the gate instead
        # of deduping on the stale refusal entry
        late = c.make_pod("a-late", tpu=1, labels={TENANT_LABEL: "a"})
        assert ext.admit(kube.pod_from_k8s(late)) is True


# -- scenario 11 (tier-1 scale) ----------------------------------------------

def test_scenario_11_tenant_serving(monkeypatch):
    """The acceptance scenario at tier-1 scale: diurnal tenant waves +
    chaos + the SLO-burn shed event, deterministic under the fixed
    seed. The scenario itself raises on quota violations, unbounded
    share spread, lost gang commits, unjournaled sheds, leaks, or
    ledger divergence."""
    from tpukube.sim import scenarios

    monkeypatch.setenv("TPUKUBE_TENANCY_WAVES", "7")
    monkeypatch.delenv("TPUKUBE_TENANCY_ENABLED", raising=False)
    r = scenarios.run(11)
    assert r["quota_violations"] == 0
    assert r["value"] is not None and r["value"] <= 2.0
    assert set(r["gangs_committed"]) == {"diurnal-train", "slo-probe"}
    assert r["preemptions"] > 0
    assert sum(r["sheds_by_tenant"].values()) > 0
    assert (sum(r["sheds_by_tenant"].values())
            == r["shed_events_journaled"])
    assert (sum(r["quota_denials_by_tenant"].values())
            == r["denial_events_journaled"] > 0)
    assert r["leaked_reservations"] == 0
    assert r["ledger_divergence"] == 0
    assert r["steady_utilization_min_percent"] >= 90

"""CFG dataflow engine (ISSUE 7): engine-level path queries over
tricky control flow, (violating, clean) fixture pairs for the
epoch-discipline and reservation-leak passes — try/finally with return
inside, with inside a loop, early return under the lock, bare raise
re-raise, nested `with A, B:` — and the mutation-kill test proving
every existing epoch-bump seam in sched/state.py + sched/gang.py is
covered: deleting any single `self._epoch += 1` makes the pass report.
"""

import ast
import os
import textwrap

from tpukube.analysis import base, cfg
from tpukube.analysis.epochs import check_epochs
from tpukube.analysis.leaks import check_leaks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sf(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return base.SourceFile(p, rel=rel)


def _func(src: str):
    return ast.parse(textwrap.dedent(src)).body[0]


def _calls(node: cfg.Node, name: str) -> bool:
    if node.stmt is None:
        return False
    return any(
        isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
        and n.func.attr == name
        for n in cfg.shallow_walk(node.stmt)
    )


def _start(g: cfg.FunctionCFG, name: str) -> cfg.Node:
    return next(n for n in g.nodes if _calls(n, name))


# -- engine ------------------------------------------------------------------

def test_return_inside_try_finally_runs_cleanup():
    """A `return` inside try/finally must route THROUGH the finally
    body — a settle there covers the early exit."""
    g = cfg.build_cfg(_func("""
        def f(self):
            self.acquire()
            try:
                return 1
            finally:
                self.settle()
    """))
    rets, rzs = cfg.escapes_function(
        g, _start(g, "acquire"), lambda n: _calls(n, "settle"))
    assert rets == [] and rzs == []


def test_loop_break_path_can_skip_settle():
    g = cfg.build_cfg(_func("""
        def f(self):
            self.acquire()
            while self.more():
                if self.bad():
                    break
                self.settle()
                return 2
            return None
    """))
    rets, rzs = cfg.escapes_function(
        g, _start(g, "acquire"), lambda n: _calls(n, "settle"))
    # two unsettled normal exits: loop-never-entered and break
    assert rets and rzs == []


def test_explicit_raise_reaches_raise_exit():
    g = cfg.build_cfg(_func("""
        def f(self):
            self.acquire()
            if self.bad():
                raise RuntimeError("boom")
            self.settle()
            return 1
    """))
    rets, rzs = cfg.escapes_function(
        g, _start(g, "acquire"), lambda n: _calls(n, "settle"))
    assert rets == [] and len(rzs) == 1


def test_handlerless_try_bodies_are_assumed_not_to_raise():
    """try/finally WITHOUT handlers signals cleanup, not expected
    exceptions: no implicit exception edges, so acquire->settle with
    plain statements between stays clean (the bind() wrapper shape)."""
    g = cfg.build_cfg(_func("""
        def f(self):
            try:
                self.acquire()
                self.other_work()
                self.settle()
                return 1
            finally:
                self.observe()
    """))
    rets, rzs = cfg.escapes_function(
        g, _start(g, "acquire"), lambda n: _calls(n, "settle"))
    assert rets == [] and rzs == []


def test_try_with_handlers_gets_implicit_exception_edges():
    g = cfg.build_cfg(_func("""
        def f(self):
            self.acquire()
            try:
                self.might_fail()
            except ValueError:
                return None
            self.settle()
            return 1
    """))
    rets, rzs = cfg.escapes_function(
        g, _start(g, "acquire"), lambda n: _calls(n, "settle"))
    # the handler's `return None` path never settles
    assert len(rets) == 1 and rzs == []


def test_region_query_sees_all_three_exit_kinds():
    src = """
        def f(self, key):
            with self._lock:
                self.seam(key)
                if key:
                    return 1
                self._epoch += 1
            return 0
    """
    g = cfg.build_cfg(_func(src), lock_attrs={"_lock"})
    start = _start(g, "seam")
    rid = g.outermost_region(start, "_lock")
    assert rid is not None

    def bump(n):
        return n.stmt is not None and any(
            isinstance(x, ast.AugAssign) for x in cfg.shallow_walk(n.stmt))

    # the `return 1` leaves the region without a bump
    assert cfg.escapes_region(g, start, rid, bump)


def test_shallow_walk_skips_nested_defs_and_lambdas():
    stmt = ast.parse(textwrap.dedent("""
        def outer(self):
            def helper():
                self.hidden_mutation()
            return max(self.xs, key=lambda v: self.also_hidden(v))
    """)).body[0]
    names = {
        n.func.attr for n in cfg.shallow_walk(stmt)
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
    }
    assert "hidden_mutation" not in names
    assert "also_hidden" not in names


# -- epoch-discipline fixture pairs ------------------------------------------

EPOCH_TRY_FINALLY_VIO = '''\
class GangManager:
    def vio(self, key):
        with self._lock:
            try:
                self._reservations.pop(key, None)
                return True
            finally:
                self._log()
'''

EPOCH_TRY_FINALLY_OK = '''\
class GangManager:
    def ok(self, key):
        with self._lock:
            try:
                self._reservations.pop(key, None)
                return True
            finally:
                self._epoch += 1
'''

EPOCH_WITH_IN_LOOP_VIO = '''\
class GangManager:
    def vio(self, keys):
        for k in keys:
            with self._lock:
                self._reservations.pop(k, None)
                if k == "skip":
                    continue
                self._epoch += 1
'''

EPOCH_WITH_IN_LOOP_OK = '''\
class GangManager:
    def ok(self, keys):
        for k in keys:
            with self._lock:
                self._reservations.pop(k, None)
                self._epoch += 1
                if k == "skip":
                    continue
'''

EPOCH_EARLY_RETURN_VIO = '''\
class GangManager:
    def vio(self, key):
        with self._lock:
            res = self._reservations.pop(key, None)
            if res is None:
                return None
            self._epoch += 1
            return res
'''

EPOCH_EARLY_RETURN_OK = '''\
class GangManager:
    def ok(self, key):
        with self._lock:
            res = self._reservations.get(key)
            if res is None:
                return None
            self._reservations.pop(key, None)
            self._epoch += 1
            return res
'''

EPOCH_BARE_RAISE_VIO = '''\
class GangManager:
    def vio(self, key, res):
        with self._lock:
            try:
                self._reservations[key] = res
                self._validate(res)
            except Exception:
                raise
            self._epoch += 1
'''

EPOCH_BARE_RAISE_OK = '''\
class GangManager:
    def ok(self, key, res):
        with self._lock:
            try:
                self._reservations[key] = res
                self._validate(res)
            except Exception:
                self._epoch += 1
                raise
            self._epoch += 1
'''

EPOCH_MULTI_WITH_VIO = '''\
class GangManager:
    def vio(self, key, res):
        with self._ttl_lock, self._lock:
            self._reservations[key] = res
        self._epoch += 1
'''

EPOCH_MULTI_WITH_OK = '''\
class GangManager:
    def ok(self, key, res):
        with self._ttl_lock, self._lock:
            self._reservations[key] = res
            self._epoch += 1
'''


def test_epoch_fixture_pairs(tmp_path):
    pairs = [
        (EPOCH_TRY_FINALLY_VIO, EPOCH_TRY_FINALLY_OK),
        (EPOCH_WITH_IN_LOOP_VIO, EPOCH_WITH_IN_LOOP_OK),
        (EPOCH_EARLY_RETURN_VIO, EPOCH_EARLY_RETURN_OK),
        (EPOCH_BARE_RAISE_VIO, EPOCH_BARE_RAISE_OK),
        (EPOCH_MULTI_WITH_VIO, EPOCH_MULTI_WITH_OK),
    ]
    for i, (vio, ok) in enumerate(pairs):
        bad = check_epochs(_sf(tmp_path, f"v{i}/sched/gang.py", vio))
        assert bad, f"pair {i}: violation not flagged"
        assert all(f.rule == "epoch-discipline" for f in bad)
        assert all("_epoch" in f.message for f in bad)
        good = check_epochs(_sf(tmp_path, f"o{i}/sched/gang.py", ok))
        assert good == [], f"pair {i}: clean twin flagged: {good}"


SNAPSHOT_SLOT_VIO = '''\
class SnapshotCache:
    def vio(self, snap, key):
        with self._lock:
            if snap.key == key:
                self._snap = snap
                return snap
            self._snap_gen += 1
'''

SNAPSHOT_SLOT_OK = '''\
class SnapshotCache:
    def ok(self, snap, key):
        with self._lock:
            if snap.key == key:
                self._snap = snap
                self._snap_gen += 1
                return snap
'''


def test_snapshot_cache_slot_writes_proven(tmp_path):
    """ISSUE 10: sched/snapshot.py owns a mutation-application seam now
    (the delta advance writes the cached-snapshot slot), so it carries
    the EPOCH_REGISTRY entry PR 6 promised — every ``_snap`` write must
    pair with a ``_snap_gen`` bump before the cache mutex releases."""
    bad = check_epochs(_sf(tmp_path, "sched/snapshot.py",
                           SNAPSHOT_SLOT_VIO))
    assert len(bad) == 1
    assert "_snap_gen" in bad[0].message
    good = check_epochs(_sf(tmp_path, "o/sched/snapshot.py",
                            SNAPSHOT_SLOT_OK))
    assert good == []


def test_snapshot_cache_mutation_kill():
    """Deleting any ``self._snap_gen += 1`` in the real snapshot.py is
    detected — the registry provably covers every slot write."""
    path = os.path.join(REPO, "tpukube", "sched", "snapshot.py")
    lines = open(path).read().splitlines(keepends=True)
    bumps = [i for i, ln in enumerate(lines)
             if ln.strip() == "self._snap_gen += 1"]
    assert bumps, "snapshot.py: no _snap_gen bumps found?"
    for i in bumps:
        mutated = list(lines)
        indent = len(lines[i]) - len(lines[i].lstrip())
        mutated[i] = " " * indent + "pass\n"
        sf = base.SourceFile(path, text="".join(mutated),
                             rel="sched/snapshot.py")
        assert check_epochs(sf), (
            f"sched/snapshot.py:{i + 1}: deleting this _snap_gen bump "
            f"went UNDETECTED"
        )


def test_epoch_seam_via_tuple_unpacking_is_not_invisible(tmp_path):
    """`self._reservations[k], old = res, None` writes the seam exactly
    like the plain form — unpacking targets must not evade the pass."""
    src = '''\
class GangManager:
    def vio(self, key, res):
        with self._lock:
            self._reservations[key], old = res, None
'''
    findings = check_epochs(_sf(tmp_path, "sched/gang.py", src))
    assert len(findings) == 1
    assert "_reservations" in findings[0].message


def test_epoch_seam_outside_lock_is_a_finding(tmp_path):
    src = '''\
class ClusterState:
    def vio(self, key, alloc):
        self._allocs[key] = alloc
        self._epoch += 1
'''
    findings = check_epochs(_sf(tmp_path, "sched/state.py", src))
    assert len(findings) == 1
    assert "outside" in findings[0].message


def test_epoch_locked_helper_checked_to_function_exit(tmp_path):
    vio = '''\
class GangManager:
    def _drop_locked(self, key):
        self._reservations.pop(key, None)
'''
    ok = vio.replace(
        "self._reservations.pop(key, None)",
        "self._reservations.pop(key, None)\n        self._epoch += 1")
    assert check_epochs(_sf(tmp_path, "a/sched/gang.py", vio))
    assert check_epochs(_sf(tmp_path, "b/sched/gang.py", ok)) == []


def test_epoch_out_of_scope_module_is_ignored(tmp_path):
    assert check_epochs(
        _sf(tmp_path, "obs/other.py", EPOCH_EARLY_RETURN_VIO)) == []


def test_epoch_findings_waivable(tmp_path):
    src = EPOCH_EARLY_RETURN_VIO.replace(
        "            res = self._reservations.pop(key, None)",
        "            # tpukube: allow(epoch-discipline) fixture: "
        "pop miss mutates nothing\n"
        "            res = self._reservations.pop(key, None)")
    sf = _sf(tmp_path, "sched/gang.py", src)
    raw = check_epochs(sf)
    assert len(raw) == 1
    assert base.apply_waivers(sf, raw) == []


# -- interprocedural delegation (ISSUE 18) -----------------------------------

DELEGATED_BUMP_OK = '''\
class GangManager:
    def drop(self, key):
        with self._lock:
            self._reservations.pop(key, None)
            self._bump_locked()

    def _bump_locked(self):
        self._epoch += 1
'''

DELEGATED_BUMP_TWO_LEVEL = '''\
class GangManager:
    def drop(self, key):
        with self._lock:
            self._reservations.pop(key, None)
            self._outer_locked()

    def _outer_locked(self):
        self._inner_locked()

    def _inner_locked(self):
        self._epoch += 1
'''

DELEGATED_BUMP_PARTIAL = '''\
class GangManager:
    def drop(self, key):
        with self._lock:
            self._reservations.pop(key, None)
            self._bump_locked(key)

    def _bump_locked(self, key):
        if key is None:
            return
        self._epoch += 1
'''


def test_delegated_bump_one_level_accepted(tmp_path):
    """`self._helper()` whose body bumps on EVERY exit discharges the
    caller's epoch obligation — the one-level interprocedural summary."""
    assert check_epochs(
        _sf(tmp_path, "sched/gang.py", DELEGATED_BUMP_OK)) == []


def test_delegated_bump_two_level_chain_rejected(tmp_path):
    """Helper summaries use the DIRECT predicate only: a helper that
    merely calls another bumping helper does not vouch — unbounded
    delegation chains would make the proof unreadable and unsound
    (the middle hop can grow a bail-out path silently)."""
    findings = check_epochs(
        _sf(tmp_path, "sched/gang.py", DELEGATED_BUMP_TWO_LEVEL))
    assert findings
    assert all(f.rule == "epoch-discipline" for f in findings)


def test_delegated_bump_partial_helper_rejected(tmp_path):
    """A helper that bumps on only SOME of its paths does not
    discharge the caller — always_satisfies demands every exit."""
    assert check_epochs(
        _sf(tmp_path, "sched/gang.py", DELEGATED_BUMP_PARTIAL))


def test_classgraph_tracks_locks_held_at_call_sites():
    from tpukube.analysis import callgraph

    tree = ast.parse(textwrap.dedent('''
        class C:
            def outer(self):
                self.before()
                with self._lock:
                    self.under()
                self.after()
    '''))
    cg = callgraph.ClassGraph(tree.body[0], lock_attrs=("_lock",))
    assert cg.sites_of("under")[0].held == frozenset({"_lock"})
    assert cg.sites_of("before")[0].held == frozenset()
    assert cg.sites_of("after")[0].held == frozenset()


# -- seam-triple mutation-kill sweep (ISSUE 18) -------------------------------

def _seam_mutants(src: str):
    """(description, first line, end line) per deletable seam site:
    every `_note_delta_locked`/`_note_journal_locked` statement-call
    and every `self._epoch += 1`."""
    out = []
    for n in ast.walk(ast.parse(src)):
        if (isinstance(n, ast.Expr) and isinstance(n.value, ast.Call)
                and isinstance(n.value.func, ast.Attribute)
                and n.value.func.attr in ("_note_delta_locked",
                                          "_note_journal_locked")):
            out.append((n.value.func.attr, n.lineno, n.end_lineno))
        elif (isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add)
                and cfg._self_attr(n.target) == "_epoch"):
            out.append(("_epoch += 1", n.lineno, n.end_lineno))
    return out


def test_seam_triple_mutation_kill_sweep():
    """Deleting ANY single delta note, journal note, or epoch bump in
    the shipped ledger/gang modules flips lint to failing — the
    bump/delta/journal triple is provably covered site by site, with
    the real waivers applied (a waiver that masked a kill would show
    up here as a survivor)."""
    from tpukube.analysis.seams import check_seam_triples

    survivors = []
    total = 0
    for rel in ("sched/state.py", "sched/gang.py"):
        path = os.path.join(REPO, "tpukube", rel)
        src = open(path).read()
        lines = src.splitlines(keepends=True)
        mutants = _seam_mutants(src)
        assert len(mutants) >= 20, f"{rel}: seam sites went missing?"
        total += len(mutants)
        for what, lo, hi in mutants:
            mutated = list(lines)
            indent = len(lines[lo - 1]) - len(lines[lo - 1].lstrip())
            mutated[lo - 1] = " " * indent + "pass\n"
            for i in range(lo, hi):
                mutated[i] = "\n"
            sf = base.SourceFile(path, text="".join(mutated), rel=rel)
            findings = base.apply_waivers(
                sf, check_seam_triples(sf) + check_epochs(sf))
            if not findings:
                survivors.append(f"{rel}:{lo} ({what})")
    assert total >= 80
    assert not survivors, (
        "deleting these seam sites went UNDETECTED: "
        + ", ".join(survivors))


# -- reservation-leak fixture pairs ------------------------------------------

LEAK_TRY_FINALLY_VIO = '''\
class Extender:
    def bind(self, key, alloc):
        try:
            self.state.commit(alloc)
            if self.broken:
                raise RuntimeError("boom")
            return alloc
        finally:
            self._observe(key)
'''

LEAK_TRY_FINALLY_OK = '''\
class Extender:
    def bind(self, key, alloc):
        try:
            self.state.commit(alloc)
            if self.broken:
                self.state.release(key)
                raise RuntimeError("boom")
            return alloc
        finally:
            self._observe(key)
'''

LEAK_EARLY_RETURN_VIO = '''\
class Extender:
    def _execute_pending_preemption(self, res):
        victims = self.gang.take_pending_victims(res)
        if not victims:
            return
        self._apply_victims(victims)
'''

LEAK_EARLY_RETURN_OK = '''\
class Extender:
    def _execute_pending_preemption(self, res):
        if not self.gang.peek_pending_victims(res):
            return
        victims = self.gang.take_pending_victims(res)
        self._apply_victims(victims)
'''

LEAK_BARE_RAISE_VIO = '''\
class Extender:
    def bind(self, key, alloc):
        self.state.commit(alloc)
        try:
            self._effector(alloc)
        except Exception:
            raise
        return alloc
'''

LEAK_BARE_RAISE_OK = '''\
class Extender:
    def bind(self, key, alloc):
        self.state.commit(alloc)
        try:
            self._effector(alloc)
        except Exception:
            self.state.release(key)
            raise
        return alloc
'''

LEAK_PLAN_DROPPED_VIO = '''\
class Extender:
    def _try_preemption(self, pod, count):
        plan = None
        for sid in self.slices:
            with self._scan_guard:
                cand = policy.find_preemption_plan(sid)
            if cand is not None:
                plan = cand
        if plan is None:
            raise GangError("no plan")
        return None
'''

LEAK_PLAN_DROPPED_OK = '''\
class Extender:
    def _try_preemption(self, pod, count):
        for sid in self.slices:
            with self._scan_guard:
                cand = policy.find_preemption_plan(sid)
            if cand is not None:
                return self.gang.reserve_exact(pod, count, cand)
        raise GangError("no plan")
'''

LEAK_RESTORE_VIO = '''\
class GangManager:
    def restore(self, namespace, group, allocs):
        with self._lock:
            sid = self._state.slice_of_node(allocs[0].node_name)
            if sid is None:
                return None
            res = self._make(group, sid)
            self._reservations[(namespace, group.name)] = res
            self._epoch += 1
            return res
'''

LEAK_RESTORE_OK = '''\
class GangManager:
    def restore(self, namespace, group, allocs):
        def rollback_all(why):
            self._note(why)

        with self._lock:
            sid = self._state.slice_of_node(allocs[0].node_name)
            if sid is None:
                rollback_all("member node unknown")
                return None
            res = self._make(group, sid)
            self._reservations[(namespace, group.name)] = res
            self._epoch += 1
            return res
'''


def test_leak_fixture_pairs(tmp_path):
    pairs = [
        ("sched/extender.py", LEAK_TRY_FINALLY_VIO, LEAK_TRY_FINALLY_OK),
        ("sched/extender.py", LEAK_EARLY_RETURN_VIO, LEAK_EARLY_RETURN_OK),
        ("sched/extender.py", LEAK_BARE_RAISE_VIO, LEAK_BARE_RAISE_OK),
        ("sched/extender.py", LEAK_PLAN_DROPPED_VIO, LEAK_PLAN_DROPPED_OK),
        ("sched/gang.py", LEAK_RESTORE_VIO, LEAK_RESTORE_OK),
    ]
    for i, (rel, vio, ok) in enumerate(pairs):
        bad = check_leaks(_sf(tmp_path, f"v{i}/{rel}", vio))
        assert bad, f"pair {i}: violation not flagged"
        assert all(f.rule == "reservation-leak" for f in bad)
        good = check_leaks(_sf(tmp_path, f"o{i}/{rel}", ok))
        assert good == [], f"pair {i}: clean twin flagged: {good}"


def test_leak_out_of_scope_is_ignored(tmp_path):
    # same code outside the registered files/functions: no findings
    assert check_leaks(
        _sf(tmp_path, "sim/other.py", LEAK_TRY_FINALLY_VIO)) == []
    renamed = LEAK_TRY_FINALLY_VIO.replace("def bind", "def helper")
    assert check_leaks(
        _sf(tmp_path, "sched/extender.py", renamed)) == []


def test_leak_findings_waivable(tmp_path):
    src = LEAK_BARE_RAISE_VIO.replace(
        "        self.state.commit(alloc)",
        "        # tpukube: allow(reservation-leak) fixture: the "
        "effector's caller releases\n"
        "        self.state.commit(alloc)")
    sf = _sf(tmp_path, "sched/extender.py", src)
    raw = check_leaks(sf)
    assert len(raw) == 1
    assert base.apply_waivers(sf, raw) == []


# -- the real tree ------------------------------------------------------------

def test_real_tree_clean_under_both_passes():
    tree = os.path.join(REPO, "tpukube")
    findings = base.run_all(
        [tree], rules=["epoch-discipline", "reservation-leak"])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_mutation_kill_every_epoch_bump_is_covered():
    """ISSUE 7 acceptance: deleting ANY single `self._epoch += 1` in
    sched/state.py or sched/gang.py makes epoch-discipline report a
    finding — the registry provably covers every existing bump seam."""
    for rel in ("sched/state.py", "sched/gang.py"):
        path = os.path.join(REPO, "tpukube", rel)
        lines = open(path).read().splitlines(keepends=True)
        bumps = [i for i, ln in enumerate(lines)
                 if ln.strip() == "self._epoch += 1"]
        assert bumps, f"{rel}: no epoch bumps found?"
        for i in bumps:
            mutated = list(lines)
            indent = len(lines[i]) - len(lines[i].lstrip())
            mutated[i] = " " * indent + "pass\n"
            sf = base.SourceFile(path, text="".join(mutated), rel=rel)
            findings = check_epochs(sf)
            assert findings, (
                f"{rel}:{i + 1}: deleting this epoch bump went "
                f"UNDETECTED — the seam it guards is not covered by "
                f"analysis/epochs.py EPOCH_REGISTRY"
            )

"""BASELINE config 3: fractional vTPU — 2 inference pods sharing 1 chip
with HBM quota enforcement.

Full stack: extender schedules both pods onto shares of the same chip over
HTTP, each pod's Allocate runs through a real device-plugin gRPC stack to
produce its container env, and a real subprocess launched with that env +
the LD_PRELOADed libhbmguard.so proves the quota actually bites (the sim
analog of the reference's CUDA-intercept enforcement, SURVEY.md §2 C6).
"""

import os
import subprocess
import sys

import pytest

from tpukube.core.config import load_config
from tpukube.device.tpu import ENV_HBM_LIMIT, ENV_MEM_FRACTION
from tpukube.sim import SimCluster

HBM = 256 << 20  # 256 MiB chips keep the enforcement subprocess fast
GUARD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tpukube", "native", "libhbmguard.so",
)


@pytest.fixture(scope="module")
def guard_lib():
    proc = subprocess.run(
        ["make", "-C", os.path.dirname(GUARD), "libhbmguard.so"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    return GUARD


def _alloc_in_guarded_process(env: dict[str, str], mib: int) -> bool:
    """Try a `mib`-MiB allocation in a subprocess running under the pod's
    env + hbmguard preload. True iff the allocation succeeded."""
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import numpy as np; np.zeros({mib} << 20, dtype=np.uint8); print('ok')"],
        env={
            **os.environ,
            **env,
            "LD_PRELOAD": GUARD,
        },
        capture_output=True, text=True, timeout=60,
    )
    if proc.returncode == 0 and "ok" in proc.stdout:
        return True
    assert "MemoryError" in proc.stderr, (
        f"allocation failed for the wrong reason:\n{proc.stderr}"
    )
    return False


def test_config3_two_pods_share_one_chip(guard_lib):
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "1,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "1,1,1",
        "TPUKUBE_HBM_BYTES_PER_CHIP": str(HBM),
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"}, vtpu_shares=2) as cluster:
        envs = []
        chips = set()
        for i in range(2):
            node, alloc = cluster.schedule(cluster.make_pod(f"infer-{i}", vtpu=1))
            assert node == "host-0-0-0"
            chips.add(alloc.device_ids[0].split("-frac")[0])
            env = cluster.execute_allocation(alloc)
            envs.append(env)

        # both pods share the SAME physical chip, with half-HBM quotas
        assert chips == {"tpu-0"}
        for env in envs:
            assert env[ENV_HBM_LIMIT] == str(HBM // 2)
            assert env[ENV_MEM_FRACTION] == "0.5000"

        # a third share does not exist
        with pytest.raises(RuntimeError, match="unschedulable"):
            cluster.schedule(cluster.make_pod("infer-2", vtpu=1))

        # enforcement: within-quota (64 MiB < 128 MiB) succeeds,
        # over-quota (200 MiB > 128 MiB) is refused in-process
        assert _alloc_in_guarded_process(envs[0], 64) is True
        assert _alloc_in_guarded_process(envs[0], 200) is False


def test_config3_quota_accumulates_not_just_single_alloc(guard_lib):
    # several small allocations crossing the quota in aggregate must fail;
    # quota is 100 MiB (not exactly 3x32) because malloc_usable_size metes
    # slightly more than the requested 32 MiB per buffer
    env = {ENV_HBM_LIMIT: str(100 << 20)}
    code = (
        "import numpy as np\n"
        "bufs = []\n"
        "try:\n"
        "    for i in range(10):\n"
        "        bufs.append(np.zeros(32 << 20, dtype=np.uint8))\n"
        "    print('allocated', len(bufs))\n"
        "except MemoryError:\n"
        "    print('refused at', len(bufs))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env, "LD_PRELOAD": GUARD},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    # 100 MiB quota / ~32 MiB metered each -> exactly 3 fit
    assert "refused at 3" in proc.stdout


def test_guard_meters_aligned_allocators(guard_lib):
    """numpy >= 1.26 obtains large buffers via posix_memalign /
    aligned_alloc; those paths must hit the quota exactly like malloc
    (round-1 gap: they sailed past it)."""
    env = {ENV_HBM_LIMIT: str(128 << 20)}
    code = (
        "import ctypes, ctypes.util\n"
        "libc = ctypes.CDLL(None, use_errno=True)\n"
        "out = ctypes.c_void_p()\n"
        "rc = libc.posix_memalign(ctypes.byref(out), 64, 64 << 20)\n"
        "assert rc == 0 and out.value, 'within-quota posix_memalign failed'\n"
        "rc = libc.posix_memalign(ctypes.byref(out), 64, 200 << 20)\n"
        "assert rc != 0, 'over-quota posix_memalign succeeded'\n"
        "libc.aligned_alloc.restype = ctypes.c_void_p\n"
        "p = libc.aligned_alloc(64, 200 << 20)\n"
        "assert not p, 'over-quota aligned_alloc succeeded'\n"
        "print('aligned allocators metered')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env, "LD_PRELOAD": GUARD},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "aligned allocators metered" in proc.stdout


def test_guard_meters_numpy_under_aligned_policy(guard_lib):
    """End-to-end: whatever allocator numpy's build uses (malloc or the
    aligned path), a quota-busting ndarray must raise MemoryError and a
    within-quota one must succeed."""
    env = {ENV_HBM_LIMIT: str(128 << 20)}
    assert _alloc_in_guarded_process(env, 64) is True
    assert _alloc_in_guarded_process(env, 200) is False


def test_guard_meters_anonymous_mmap(guard_lib):
    """Direct anonymous maps (Python's mmap module) are metered too, and
    munmap returns the quota."""
    env = {ENV_HBM_LIMIT: str(128 << 20)}
    code = (
        "import mmap\n"
        "m = mmap.mmap(-1, 64 << 20)\n"
        "try:\n"
        "    m2 = mmap.mmap(-1, 200 << 20)\n"
        "    raise SystemExit('over-quota mmap succeeded')\n"
        "except (OSError, MemoryError):\n"
        "    pass\n"
        "m.close()\n"  # munmap returns the quota...
        "m3 = mmap.mmap(-1, 100 << 20)\n"  # ...so this fits again
        "m3.close()\n"
        "print('mmap metered')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env, "LD_PRELOAD": GUARD},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "mmap metered" in proc.stdout


def test_guard_inert_without_limit(guard_lib):
    # no TPU_HBM_LIMIT_BYTES -> the shim must not interfere at all
    proc = subprocess.run(
        [sys.executable, "-c",
         "import numpy as np; np.zeros(300 << 20, dtype=np.uint8); print('ok')"],
        env={**{k: v for k, v in os.environ.items() if k != "TPU_HBM_LIMIT_BYTES"},
             "LD_PRELOAD": GUARD},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0 and "ok" in proc.stdout, proc.stderr

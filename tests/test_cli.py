"""C13 CLI: scenario runner, tpukubectl inspection, extender daemon main."""

import io
import json
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from tpukube import cli
from tpukube.core.config import load_config
from tpukube.core.types import PodGroup
from tpukube.sim import SimCluster, scenarios


def test_scenarios_one_through_four():
    r1 = scenarios.run(1)
    assert r1["scenario"] == 1
    assert r1["devices"] == ["tpu-0"] or len(r1["devices"]) == 1
    assert "TPU_VISIBLE_DEVICES" in r1["env_keys"]

    r2 = scenarios.run(2)
    assert len(r2["placements"]) == 4
    assert r2["utilization_percent"] == 50.0  # 4 of 8 chips

    r3 = scenarios.run(3)
    assert r3["shared_one_chip"] is True
    assert all(p["hbm_limit"] is not None for p in r3["pods"])

    r4 = scenarios.run(4)
    assert r4["contiguous"] is True
    assert r4["utilization_percent"] == pytest.approx(100 * 24 / 64)


def test_main_sim_prints_one_json_line(capsys):
    rc = cli.main_sim(["1"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    doc = json.loads(out[0])
    assert doc["scenario"] == 1


@pytest.fixture(scope="module")
def live_cluster():
    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        group = PodGroup("g", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"m-{i}", tpu=1, priority=5, group=group))
        c.schedule(c.make_pod("solo", tpu=1))
        yield c


def _ctl(live_cluster, *argv) -> tuple[int, str]:
    buf = io.StringIO()
    real_stdout = sys.stdout
    sys.stdout = buf
    try:
        rc = cli.main_ctl(["--server", live_cluster.base_url, *argv])
    finally:
        sys.stdout = real_stdout
    return rc, buf.getvalue()


def test_ctl_topo(live_cluster):
    rc, out = _ctl(live_cluster, "topo")
    assert rc == 0
    assert "util 31.25%" in out  # 5 of 16 chips
    assert "z=0" in out
    # 5 allocated chips drawn as '#' in the grid rows (legend excluded)
    grid_rows = [l for l in out.splitlines() if l.startswith("  ")]
    assert sum(line.count("#") for line in grid_rows) == 5
    # sim nodes ride runtime-equivalent inventory: no fallback banner
    assert "table-fallback" not in out


def test_ctl_alloc_and_gangs(live_cluster):
    rc, out = _ctl(live_cluster, "alloc")
    assert rc == 0
    assert out.count("\n") == 5
    assert "default/solo" in out

    rc, out = _ctl(live_cluster, "gangs")
    assert rc == 0
    assert "default/g" in out
    assert "committed" in out
    assert "4/4 bound" in out

    rc, out = _ctl(live_cluster, "--json", "gangs")
    assert json.loads(out)[0]["group"] == "g"


def test_ctl_metrics(live_cluster):
    rc, out = _ctl(live_cluster, "metrics")
    assert rc == 0
    assert "tpu_chip_utilization_percent" in out


def test_ctl_replay_roundtrip(live_cluster, tmp_path):
    events = live_cluster.extender.trace.events()
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    rc, out = _ctl(live_cluster, "replay", str(path))
    assert rc == 0
    assert "0 divergences" in out

    # corrupt one response -> nonzero exit + divergence report
    events = [dict(e) for e in events]
    bind = next(e for e in events if e["kind"] == "bind")
    bind["response"] = {"Error": "tampered"}
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    rc, out = _ctl(live_cluster, "replay", str(path))
    assert rc == 1
    assert "divergence at seq" in out


def test_ctl_bearer_token_against_secured_extender(tmp_path):
    """tpukubectl speaks the extender's bearer auth: without
    --token-file a secured daemon answers 401; with it, topo renders."""
    import urllib.error

    from tpukube.core.config import load_config
    from tpukube.sched.extender import Extender, make_app
    from tpukube.sim.harness import _AppThread, _free_port

    ext = Extender(load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    port = _free_port()
    app = _AppThread(make_app(ext, auth_token="tok"), "127.0.0.1", port)
    app.start()
    token_file = tmp_path / "token"
    token_file.write_text("tok\n")
    fake = type("L", (), {"base_url": f"http://127.0.0.1:{port}"})()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _ctl(fake, "topo")
        assert e.value.code == 401
        rc, out = _ctl(fake, "--token-file", str(token_file), "topo")
        assert rc == 0 and "util" in out
        # /metrics is deliberately open — works without the token too
        rc, out = _ctl(fake, "metrics")
        assert rc == 0 and "tpu_chip_utilization_percent" in out
    finally:
        app.stop()


def test_extender_daemon_subprocess():
    """tpukube-extender really serves the webhook API as a daemon."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpukube.cli", "extender",
         "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        deadline = time.monotonic() + 15
        last = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2
                ) as r:
                    doc = json.loads(r.read())
                assert doc["ok"] is True
                break
            except Exception as e:  # noqa: BLE001 — retry until deadline
                last = e
                time.sleep(0.2)
        else:
            pytest.fail(f"extender daemon never came up: {last}")
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_ctl_topo_multislice(tmp_path):
    """tpukubectl topo renders ONE occupancy grid per ICI slice on a
    multi-slice (DCN) cluster — coords are slice-local, so a merged
    grid would overlay unrelated chips."""
    from tpukube.core.mesh import MeshSpec

    slices = {"slice-a": MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1)),
              "slice-b": MeshSpec(dims=(2, 2, 1), host_block=(2, 2, 1))}
    with SimCluster(load_config(env={}), slices=slices) as c:
        c.schedule(c.make_pod("p0", tpu=2))
        rc, out = _ctl(c, "topo")  # _ctl only needs .base_url
        assert rc == 0
        assert "mesh None" not in out  # slice headers carry the dims
        assert "slice slice-a" in out
        assert "slice slice-b" in out
        assert out.count("z=0") == 2  # one grid per slice
        assert "#" in out             # the allocation is drawn

"""tpukube-lint (ISSUE 3): fixture tests proving each static pass
catches a seeded violation (and passes its clean twin), the waiver
mechanism, the tier-1 run over the REAL tree asserting zero unwaived
findings, and the dynamic lock-order detector — zero inversion cycles
across sim scenarios 1-7 plus a concurrent stress drive, and a seeded
inversion it must catch."""

import os
import threading

from tpukube.analysis import base, lockgraph
from tpukube.analysis.consistency import (
    check_names,
    check_rules_file,
    check_snapshot_discipline,
)
from tpukube.analysis.hygiene import check_exceptions
from tpukube.analysis.locks import (
    check_lock_discipline,
    check_lock_order,
    check_shared_state,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "tpukube")


def _sf(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return base.SourceFile(p, rel=rel)


# -- lock-discipline ---------------------------------------------------------

VIOLATING_DISCIPLINE = '''\
import time

class GangManager:
    def bad_write(self):
        with self._lock:
            self._sink_file.write("line")

    def bad_sleep(self):
        with self._decision_lock:
            time.sleep(0.1)

    def bad_open(self):
        with self._pending_lock:
            with open("/tmp/x", "w") as f:
                pass
'''

CLEAN_DISCIPLINE = '''\
import time

class GangManager:
    def good(self):
        with self._lock:
            self._queue.append("line")   # enqueue only
        self._sink_file.write("line")    # I/O outside the lock
        time.sleep(0.0)
'''


def test_lock_discipline_catches_and_passes(tmp_path):
    sf = _sf(tmp_path, "sched/gang.py", VIOLATING_DISCIPLINE)
    findings = check_lock_discipline(sf)
    assert len(findings) == 3
    assert all(f.rule == "lock-discipline" for f in findings)
    assert any(".write()" in f.message for f in findings)
    assert any(".sleep()" in f.message for f in findings)
    assert any("open()" in f.message for f in findings)
    assert check_lock_discipline(
        _sf(tmp_path, "sched/extender.py", CLEAN_DISCIPLINE)) == []
    # out-of-scope module: the same code is fine elsewhere (the sink
    # drain thread legitimately writes under ITS lock)
    assert check_lock_discipline(
        _sf(tmp_path, "obs/other.py", VIOLATING_DISCIPLINE)) == []


# -- lock-order --------------------------------------------------------------

VIOLATING_ORDER = '''\
class Extender:
    def bad_nesting(self):
        with self._pending_lock:
            with self._decision_lock:   # 0 under 1: inversion
                pass

    def bad_call(self, body):
        with self._pending_lock:
            self.handle("release", body)   # re-enters the decision lock
'''

CLEAN_ORDER = '''\
class Extender:
    def good(self, body):
        with self._decision_lock:
            with self._pending_lock:
                pass
            self.gang.sweep()
            self.state.release("k")
'''


def test_lock_order_catches_inversions(tmp_path):
    findings = check_lock_order(_sf(tmp_path, "sched/extender.py",
                                    VIOLATING_ORDER))
    assert len(findings) == 2
    assert all("decision -> pending -> gang -> ledger" in f.message
               for f in findings)
    assert check_lock_order(_sf(tmp_path, "sched/extender.py",
                                CLEAN_ORDER)) == []


def test_lock_passes_see_single_statement_multi_item_with(tmp_path):
    """`with A, B:` acquires left to right exactly like nesting — the
    compact spelling must not dodge either lock pass."""
    order = '''\
class Extender:
    def bad(self):
        with self._pending_lock, self._decision_lock:
            pass
'''
    findings = check_lock_order(_sf(tmp_path, "sched/extender.py", order))
    assert len(findings) == 1 and findings[0].rule == "lock-order"
    discipline = '''\
class ClusterState:
    def bad(self):
        with self._lock, open("/tmp/x") as f:
            pass
'''
    findings = check_lock_discipline(
        _sf(tmp_path, "sched/state.py", discipline))
    assert len(findings) == 1 and "open()" in findings[0].message


def test_lock_order_gang_ledger_direction(tmp_path):
    # gang -> ledger is the declared direction: clean
    src = '''\
class GangManager:
    def good(self):
        with self._lock:
            self._state.release("k")
'''
    assert check_lock_order(_sf(tmp_path, "sched/gang.py", src)) == []


# -- shared-state ------------------------------------------------------------

VIOLATING_SHARED = '''\
class GangManager:
    def __init__(self):
        self._reservations = {}          # exempt: no concurrency yet

    def bad(self, key, res):
        self._reservations[key] = res    # no lock held

    def good(self, key):
        with self._lock:
            return self._reservations.get(key)

    def _rollback_locked(self, res):
        self._reservations.pop(res, None)   # exempt: *_locked contract
'''


def test_shared_state_catches_unlocked_access(tmp_path):
    findings = check_shared_state(_sf(tmp_path, "sched/gang.py",
                                      VIOLATING_SHARED))
    assert len(findings) == 1
    assert findings[0].rule == "shared-state"
    assert "_reservations" in findings[0].message
    assert findings[0].line == 6


# -- name-consistency --------------------------------------------------------

def test_name_consistency_reasons_and_series(tmp_path):
    src = '''\
def wire(reg, journal):
    journal.emit("GangComited", obj="gang/x")      # typo'd reason
    journal.emit("GangCommitted", obj="gang/x")    # declared: fine
    reg.counter("tpukube_bogus_total")             # undeclared series
    reg.counter("tpukube_binds_total")             # declared: fine
'''
    findings = check_names(_sf(tmp_path, "obs/wiring.py", src))
    assert len(findings) == 2
    assert any("GangComited" in f.message for f in findings)
    assert any("tpukube_bogus_total" in f.message for f in findings)


def test_rules_file_check_catches_unrendered_series(tmp_path):
    bad = tmp_path / "rules.yaml"
    bad.write_text(
        "apiVersion: monitoring.coreos.com/v1\n"
        "kind: PrometheusRule\n"
        "spec:\n"
        "  groups:\n"
        "    - name: g\n"
        "      rules:\n"
        "        - record: r\n"
        "          expr: rate(tpukube_nonexistent_total[5m])\n"
    )
    findings = check_rules_file(bad)
    assert len(findings) == 1
    assert "tpukube_nonexistent_total" in findings[0].message
    # the shipped rules file is clean against the declared registry
    assert check_rules_file(
        os.path.join(REPO, "deploy", "prometheus-rules.yaml")) == []


# -- snapshot-discipline -----------------------------------------------------

VIOLATING_SNAPSHOT = '''\
from tpukube.sched import slicefit
from tpukube.sched.slicefit import _Sweep, occupancy_grid

def rebuild_per_webhook(mesh, occupied):
    grid = slicefit.occupancy_grid(mesh, occupied)   # finding
    sweep = _Sweep(mesh, grid)                       # finding
    return sweep

def qualified(mesh, grid):
    return slicefit._Sweep(mesh, grid)               # finding
'''

CLEAN_SNAPSHOT = '''\
def through_the_cache(extender, sid):
    ss = extender.snapshots.current().slice(sid)
    return ss.blocked_sweep()

def request_specific(mesh, blocked):
    from tpukube.sched.snapshot import sweep_for
    return sweep_for(mesh, blocked)
'''


def test_snapshot_discipline_catches_and_passes(tmp_path):
    findings = check_snapshot_discipline(
        _sf(tmp_path, "sched/extender.py", VIOLATING_SNAPSHOT))
    assert len(findings) == 3
    assert all(f.rule == "snapshot-discipline" for f in findings)
    assert any("occupancy_grid" in f.message for f in findings)
    assert any("_Sweep" in f.message for f in findings)
    assert check_snapshot_discipline(
        _sf(tmp_path, "sched/policy.py", CLEAN_SNAPSHOT)) == []
    # the defining modules keep their own constructor seams
    assert check_snapshot_discipline(
        _sf(tmp_path, "sched/snapshot.py", VIOLATING_SNAPSHOT)) == []
    assert check_snapshot_discipline(
        _sf(tmp_path, "sched/slicefit.py", VIOLATING_SNAPSHOT)) == []


def test_snapshot_discipline_waivable(tmp_path):
    src = (
        "from tpukube.sched.slicefit import occupancy_grid\n"
        "def special(mesh, occ):\n"
        "    # tpukube: allow(snapshot-discipline) one-off debug dump\n"
        "    return occupancy_grid(mesh, occ)\n"
    )
    sf = _sf(tmp_path, "sched/tooling.py", src)
    raw = check_snapshot_discipline(sf)
    assert len(raw) == 1
    assert base.apply_waivers(sf, raw) == []


# ISSUE 8: the batch planner's stricter arm — no SnapshotCache read or
# ad-hoc sweep outside the one pinning seam. A consumer quietly taking
# a second snapshot mid-batch forks the cluster view the plan answers
# from.

VIOLATING_CYCLE = '''\
from tpukube.sched.snapshot import sweep_for

class SchedulingCycle:
    def _pin_snapshot(self):
        return self._ext.snapshots.current()      # the one allowed seam

    def _plan_pod(self, pod):
        snap = self._ext.snapshots.current()      # finding: second read
        self._ext.snapshots.observe()             # finding: observer read
        return sweep_for(snap.mesh, set())        # finding: ad-hoc sweep
'''

CLEAN_CYCLE = '''\
class SchedulingCycle:
    def _pin_snapshot(self):
        return self._ext.snapshots.current()

    def _plan_pod(self, pod, snap):
        self.cycle_hist.observe(0.5)              # histogram, not a cache
        return snap.slice("s0").blocked_sweep()   # the pinned snapshot
'''


def test_cycle_snapshot_discipline_catches_and_passes(tmp_path):
    findings = check_snapshot_discipline(
        _sf(tmp_path, "sched/cycle.py", VIOLATING_CYCLE))
    assert len(findings) == 3
    assert all(f.rule == "snapshot-discipline" for f in findings)
    assert all("_pin_snapshot" in f.message for f in findings)
    assert check_snapshot_discipline(
        _sf(tmp_path, "sched/cycle.py", CLEAN_CYCLE)) == []
    # the same source OUTSIDE cycle.py is judged by the general rule
    # only (cache reads are fine there; it has no sweep constructors)
    assert check_snapshot_discipline(
        _sf(tmp_path, "sched/other.py", CLEAN_CYCLE)) == []


def test_cycle_snapshot_discipline_waivable(tmp_path):
    src = (
        "class C:\n"
        "    def helper(self):\n"
        "        # tpukube: allow(snapshot-discipline) audit-only read\n"
        "        return self._ext.snapshots.observe()\n"
    )
    sf = _sf(tmp_path, "sched/cycle.py", src)
    raw = check_snapshot_discipline(sf)
    assert len(raw) == 1
    assert base.apply_waivers(sf, raw) == []


def test_shipped_cycle_module_is_snapshot_disciplined():
    path = os.path.join(REPO, "tpukube", "sched", "cycle.py")
    sf = base.SourceFile(path, rel="sched/cycle.py")
    assert base.apply_waivers(sf, check_snapshot_discipline(sf)) == []


# -- exception-hygiene -------------------------------------------------------

def test_exception_hygiene_catches_silent_broad_except(tmp_path):
    src = '''\
import logging
log = logging.getLogger("x")

def silent():
    try:
        work()
    except Exception:
        pass

def logged():
    try:
        work()
    except Exception:
        log.exception("work failed")

def reraised():
    try:
        work()
    except BaseException:
        raise

def narrow():
    try:
        work()
    except ValueError:
        pass
'''
    findings = check_exceptions(_sf(tmp_path, "sched/helper.py", src))
    assert len(findings) == 1
    assert findings[0].line == 7


# -- waivers -----------------------------------------------------------------

def test_waiver_suppresses_and_bare_waiver_is_an_error(tmp_path):
    waived = '''\
def silent():
    try:
        work()
    # tpukube: allow(exception-hygiene) fixture: the error is recorded by the caller
    except Exception:
        pass
'''
    (tmp_path / "a").mkdir()
    f = tmp_path / "a" / "mod.py"
    f.write_text(waived)
    assert base.run_all([f]) == []

    bare = waived.replace(
        " fixture: the error is recorded by the caller", "")
    f.write_text(bare)
    findings = base.run_all([f])
    assert [x.rule for x in findings] == ["bare-waiver"]
    assert "no justification" in findings[0].message

    unknown = waived.replace("exception-hygiene",
                             "exception-hygiene, made-up-rule")
    f.write_text(unknown)
    findings = base.run_all([f])
    assert [x.rule for x in findings] == ["bare-waiver"]
    assert "made-up-rule" in findings[0].message


def test_unused_waiver_is_a_finding(tmp_path):
    """Satellite: a waiver that suppresses zero findings is stale and
    must not outlive the code it excused."""
    stale = '''\
def fine():
    # tpukube: allow(exception-hygiene) nothing here needs this anymore
    return 1
'''
    (tmp_path / "a").mkdir()
    f = tmp_path / "a" / "mod.py"
    f.write_text(stale)
    findings = base.run_all([f])
    assert [x.rule for x in findings] == ["unused-waiver"]
    assert "suppressed no findings" in findings[0].message

    # the same waiver actually suppressing something: NOT stale
    used = '''\
def silent():
    try:
        work()
    # tpukube: allow(exception-hygiene) fixture: caller records the error
    except Exception:
        pass
'''
    f.write_text(used)
    assert base.run_all([f]) == []


def test_unused_waiver_skipped_when_its_rule_did_not_run(tmp_path):
    """A partial --rules run proves nothing about a waiver for a
    deselected rule — no false staleness."""
    stale = '''\
def fine():
    # tpukube: allow(exception-hygiene) justified but stale
    return 1
'''
    (tmp_path / "a").mkdir()
    f = tmp_path / "a" / "mod.py"
    f.write_text(stale)
    findings = base.run_all(
        [f], rules=["lock-discipline", "unused-waiver", "bare-waiver"])
    assert findings == []


def test_unused_waiver_is_not_itself_waivable(tmp_path):
    """The meta rules cannot excuse themselves: naming unused-waiver
    (or bare-waiver) in a pragma is a bare-waiver finding."""
    src = '''\
def fine():
    # tpukube: allow(unused-waiver) meta rules are not waivable
    return 1
'''
    (tmp_path / "a").mkdir()
    f = tmp_path / "a" / "mod.py"
    f.write_text(src)
    findings = base.run_all([f])
    assert "bare-waiver" in [x.rule for x in findings]


def test_known_rules_message_excludes_meta_rules_by_name(tmp_path):
    """Satellite: the 'known rules' message is built from WAIVABLE_RULES
    (by name), not a positional ALL_RULES[:-1] slice that broke the day
    rules were appended after bare-waiver."""
    assert "bare-waiver" not in base.WAIVABLE_RULES
    assert "unused-waiver" not in base.WAIVABLE_RULES
    assert "epoch-discipline" in base.WAIVABLE_RULES
    assert "reservation-leak" in base.WAIVABLE_RULES
    src = '''\
def fine():
    # tpukube: allow(made-up-rule) whatever
    return 1
'''
    sf = _sf(tmp_path, "mod.py", src)
    findings = base.waiver_findings(sf)
    assert len(findings) == 1
    assert "bare-waiver" not in findings[0].message.split("known: ")[1]
    assert "epoch-discipline" in findings[0].message


def test_changed_mode_lints_only_files_changed_vs_ref(tmp_path):
    """Satellite: tpukube-lint --changed [REF] for the fast pre-commit
    loop — only changed/untracked .py files are linted."""
    import subprocess

    from tpukube.analysis.cli import main

    repo = tmp_path / "repo"
    (repo / "sched").mkdir(parents=True)

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    clean = "def fine():\n    return 1\n"
    (repo / "sched" / "gang.py").write_text(clean)
    (repo / "other.py").write_text(clean)
    git("add", "-A")
    git("commit", "-qm", "seed")

    # nothing changed: clean exit, nothing linted (paths before the
    # flag — a bare `--changed path` would bind the path as the ref)
    assert main([str(repo), "--changed"]) == 0

    # a committed file changes to a violation: --changed catches it
    (repo / "sched" / "gang.py").write_text(VIOLATING_DISCIPLINE)
    assert main([str(repo), "--changed"]) == 1

    # vs a ref where that change is already committed: nothing to lint
    git("add", "-A")
    git("commit", "-qm", "violation")
    assert main([str(repo), "--changed=HEAD"]) == 0
    # ...but vs the PREVIOUS commit the violation is a changed file
    assert main([str(repo), "--changed=HEAD~1"]) == 1

    # untracked new files are part of the pre-commit loop — also when
    # the linted path is a SUBDIRECTORY of the repo (ls-files --others
    # must run from the toplevel or it prints subtree-relative names
    # that resolve to nonexistent paths and get dropped)
    (repo / "sched" / "state.py").write_text(VIOLATING_DISCIPLINE)
    out = base.changed_paths([repo], ref="HEAD")
    assert [p.name for p in out] == ["state.py"]
    out = base.changed_paths([repo / "sched"], ref="HEAD")
    assert [p.name for p in out] == ["state.py"]
    assert main([str(repo), "--changed"]) == 1
    assert main([str(repo / "sched"), "--changed"]) == 1

    # a bad ref is a usage error (exit 2), not findings
    assert main([str(repo), "--changed=no-such-ref"]) == 2

    # the prometheus-rules cross-check survives changed-only mode: the
    # rules file is discovered from the ORIGINAL path argument, not the
    # substituted changed-file list (whose parents have no deploy/)
    (repo / "deploy").mkdir()
    (repo / "deploy" / "prometheus-rules.yaml").write_text(
        "apiVersion: monitoring.coreos.com/v1\n"
        "kind: PrometheusRule\n"
        "spec:\n"
        "  groups:\n"
        "    - name: g\n"
        "      rules:\n"
        "        - record: r\n"
        "          expr: rate(tpukube_nonexistent_total[5m])\n"
    )
    (repo / "sched" / "state.py").write_text(clean)
    (repo / "sched" / "gang.py").write_text(clean)
    git("add", "-A")
    git("commit", "-qm", "rules")
    (repo / "sched" / "gang.py").write_text(clean + "\n# touched\n")
    assert main([str(repo), "--changed"]) == 1  # rules-file finding

    # ...and even with ZERO changed .py files ("only the rules file
    # changed" is exactly when the cross-check matters most)
    git("add", "-A")
    git("commit", "-qm", "touch")
    assert main([str(repo), "--changed"]) == 1


def test_unparseable_file_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "broken.py").write_text("def broken(:\n    pass\n")
    findings = base.run_all([tmp_path])
    assert [f.rule for f in findings] == ["parse-error"]
    from tpukube.analysis.cli import main

    assert main([str(tmp_path)]) == 1  # pointed finding, no traceback


# -- seam-triple (ISSUE 18) --------------------------------------------------

def _seam_registry():
    from tpukube.analysis import seams

    return {("sched/ledger.py", "Ledger"): seams.TripleSpec(
        lock_attr="_lock",
        journal_exempt=frozenset({"replay"}),
    )}


VIOLATING_SEAMS = '''\
class Ledger:
    def missing_journal(self, key):
        with self._lock:
            self._map[key] = 1
            self._epoch += 1
            self._note_delta_locked(slices=(key,), why="x")

    def missing_both_on_branch(self, key, fast):
        with self._lock:
            self._epoch += 1
            if fast:
                return None
            self._note_delta_locked(slices=(key,), why="x")
            self._note_journal_locked("k", {"key": key})

    def raises_before_journal(self, key):
        with self._lock:
            self._epoch += 1
            self._note_delta_locked(slices=(key,), why="x")
            if key is None:
                raise ValueError("bad key")
            self._note_journal_locked("k", {"key": key})

    def double_bump_one_delta(self, a):
        with self._lock:
            self._epoch += 1
            self._epoch += 1
            self._note_delta_locked(slices=(a,), why="x")
            self._note_journal_locked("k", {"a": a})
'''

CLEAN_SEAMS = '''\
class Ledger:
    def commit(self, key):
        with self._lock:
            self._map[key] = 1
            self._epoch += 1
            self._note_delta_locked(slices=(key,), why="commit")
            self._note_journal_locked("k", {"key": key})

    def replay(self, doc):
        with self._lock:
            self._epoch += 1
            self._note_delta_locked(slices=(doc,), why="replay")

    def _drop_locked(self, key):
        self._map.pop(key, None)
        self._epoch += 1
        self._note_delta_locked(slices=(key,), why="drop")
        self._note_journal_locked("k", {"key": key})
'''


def test_seam_triple_catches_and_passes(tmp_path):
    from tpukube.analysis.seams import check_seam_triples

    reg = _seam_registry()
    sf = _sf(tmp_path, "sched/ledger.py", VIOLATING_SEAMS)
    findings = check_seam_triples(sf, registry=reg)
    assert all(f.rule == "seam-triple" for f in findings)
    msgs = [f.message for f in findings]
    # one per seeded hole: missing journal half, both halves on the
    # early-return branch, the raise path, and the bump-to-bump gap
    assert any("_note_journal_locked" in m and "missing_journal" in m
               for m in msgs)
    assert sum("missing_both_on_branch" in m for m in msgs) == 2
    assert any("exception path" in m and "raises_before_journal" in m
               for m in msgs)
    assert any("reaches the next bump" in m for m in msgs)
    assert len(findings) == 5
    assert check_seam_triples(
        _sf(tmp_path, "o/sched/ledger.py", CLEAN_SEAMS),
        registry=reg) == []


def test_seam_triple_raise_path_waivable_without_masking_bump(tmp_path):
    """The raise-path finding anchors at the RAISE, not the bump — a
    deliberate mutate-then-raise design gets waived there while the
    same bump's normal-path obligations stay enforced."""
    from tpukube.analysis.seams import check_seam_triples

    src = VIOLATING_SEAMS.replace(
        "            if key is None:\n"
        "                raise ValueError(\"bad key\")",
        "            if key is None:\n"
        "                # tpukube: allow(seam-triple) fixture: the "
        "failed-validation raise is not journaled by design\n"
        "                raise ValueError(\"bad key\")")
    sf = _sf(tmp_path, "sched/ledger.py", src)
    raw = check_seam_triples(sf, registry=_seam_registry())
    kept = base.apply_waivers(sf, raw)
    assert len(kept) == len(raw) - 1
    assert not any("exception path" in f.message for f in kept)


def test_seam_triple_required_kinds_catch_deleted_journal_site(tmp_path):
    """Deleting a journal-ONLY note (no bump beside it) is caught by
    kind coverage: the replayer still dispatches on the string, so a
    file that stops noting it has a dead recovery seam."""
    from tpukube.analysis.seams import check_seam_triples

    src = '''\
class ClusterState:
    def note_some(self):
        with self._lock:
            self._note_journal_locked("node", {})
            self._note_journal_locked("nodes", {})
            self._note_journal_locked("commit", {})
            self._note_journal_locked("cordon", {})
            self._note_journal_locked("unnodes", {})
'''
    findings = check_seam_triples(_sf(tmp_path, "sched/state.py", src))
    assert len(findings) == 1
    assert '"release"' in findings[0].message


# -- flag-discipline (ISSUE 18) ----------------------------------------------

def _flag_registry():
    from tpukube.analysis import flags

    return (flags.FlagSpec(
        flag="widget_enabled",
        ctors=frozenset({"WidgetRing"}),
        construct_scope=("sched/widgets.py",),
        attr="widgets",
        consumers=(("sched/widgets.py", "Owner"),),
    ),)


VIOLATING_FLAGS = '''\
class Owner:
    def __init__(self, config):
        self.widgets = WidgetRing(config)

    def use(self):
        return self.widgets.count()
'''

CLEAN_FLAGS = '''\
class Owner:
    def __init__(self, config):
        self.widgets = (WidgetRing(config)
                        if config.widget_enabled else None)

    def use(self):
        if self.widgets is None:
            return 0
        return self.widgets.count()

    def inline(self):
        return (self.widgets.count()
                if self.widgets is not None else 0)

    def flag_named_block(self, config):
        if config.widget_enabled:
            return self.widgets.count()
        return 0
'''


def test_flag_discipline_catches_and_passes(tmp_path):
    from tpukube.analysis.flags import check_flags

    reg = _flag_registry()
    sf = _sf(tmp_path, "sched/widgets.py", VIOLATING_FLAGS)
    findings = check_flags(sf, registry=reg)
    assert len(findings) == 2
    assert any("constructed without" in f.message for f in findings)
    assert any("is None` guard" in f.message for f in findings)
    assert check_flags(
        _sf(tmp_path, "o/sched/widgets.py", CLEAN_FLAGS),
        registry=reg) == []
    # out of scope: the same code elsewhere is not this pass's business
    assert check_flags(
        _sf(tmp_path, "obs/other.py", VIOLATING_FLAGS),
        registry=reg) == []


def test_flag_discipline_registry_rot_against_config(tmp_path):
    """A FLAG_REGISTRY entry whose flag is not a config field gates
    nothing — flagged when linting core/config.py."""
    from tpukube.analysis.flags import check_flags

    src = '''\
class TpuKubeConfig:
    decisions_enabled: bool = False
'''
    findings = check_flags(_sf(tmp_path, "core/config.py", src),
                           registry=_flag_registry())
    assert len(findings) == 1
    assert "widget_enabled" in findings[0].message


def test_flag_discipline_shipped_registry_matches_config():
    """Every shipped FLAG_REGISTRY flag is a real TpuKubeConfig field."""
    from tpukube.analysis.flags import FLAG_REGISTRY
    from tpukube.core.config import TpuKubeConfig

    for spec in FLAG_REGISTRY:
        assert hasattr(TpuKubeConfig, spec.flag) or \
            spec.flag in TpuKubeConfig.__annotations__


# -- name-consistency reverse audit (ISSUE 18) --------------------------------

def test_registry_rot_reverse_audit(tmp_path):
    """A declared series/reason whose last reference site was deleted
    is a finding on the DECLARING file — dashboards and rules keep
    resolving the name while nothing serves it."""
    _sf(tmp_path, "obs/render.py",
        'SERIES = "tpukube_used_series"\n'
        'REASON = "UsedReason"\n')
    reg = _sf(tmp_path, "obs/registry.py", '''\
DECLARED_SERIES = frozenset({
    "tpukube_used_series",
    "tpukube_rotten_series",
})
''')
    findings = check_names(reg)
    assert len(findings) == 1
    assert "tpukube_rotten_series" in findings[0].message
    assert findings[0].line == 3

    ev = _sf(tmp_path, "obs/events.py", '''\
REASONS = (
    "UsedReason",
    "GhostReason",
)
''')
    findings = check_names(ev)
    assert len(findings) == 1
    assert "GhostReason" in findings[0].message


# -- the real tree (tier-1 acceptance) ---------------------------------------

def test_tree_is_clean():
    """`tpukube-lint tpukube/` exits 0 on the shipped tree: every pass,
    the prometheus-rules cross-check, and the waiver lint together
    produce zero unwaived findings."""
    findings = base.run_all([TREE])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes(tmp_path, capsys):
    from tpukube.analysis.cli import main

    assert main([TREE]) == 0
    assert "clean" in capsys.readouterr().out
    p = tmp_path / "sched"
    p.mkdir()
    (p / "gang.py").write_text(VIOLATING_DISCIPLINE)
    assert main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "lock-discipline" in out and "finding(s)" in out
    import json

    assert main(["--json", str(tmp_path)]) == 1
    lines = [json.loads(L) for L in
             capsys.readouterr().out.strip().splitlines()]
    assert all(L["rule"] == "lock-discipline" for L in lines)
    assert main(["--list-rules"]) == 0
    capsys.readouterr()
    # usage errors are exit 2, distinct from findings (exit 1)
    assert main(["--rules", "made-up-rule", TREE]) == 2
    assert main(["--rules-file", "/no/such/rules.yaml", TREE]) == 2
    capsys.readouterr()


# -- dynamic lock-order detector ---------------------------------------------

def test_monitor_off_by_default():
    """The instrumented-lock mode is opt-in with zero overhead when
    off: the default config leaves it disabled and the threading
    factories untouched (the bench guard for the scenario-5 churn
    phase — no proxy exists to slow an uninstrumented run)."""
    from tpukube.core.config import load_config

    assert load_config(env={}).lock_monitor is False
    assert threading.Lock is lockgraph._REAL_LOCK
    assert threading.RLock is lockgraph._REAL_RLOCK


def test_monitor_records_and_detects_seeded_inversion(tmp_path):
    """The detector's own fixture: two locks taken in opposite orders
    from the same thread must report a cycle (the deadlock the static
    pass cannot see across functions)."""
    with lockgraph.monitor(scope=None) as mon:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    cycles = mon.cycles()
    assert len(cycles) == 1
    assert len(cycles[0]) == 2
    # and the factories were restored on exit
    assert threading.Lock is lockgraph._REAL_LOCK


def test_monitor_sees_dataclass_default_factory_locks():
    """The DecisionTrace ring lock is created through a dataclass
    default_factory — it must still resolve the PATCHED threading.Lock
    at instance-creation time, not a factory captured at import."""
    from tpukube.trace import DecisionTrace

    with lockgraph.monitor() as mon:
        t = DecisionTrace(capacity=4)
        t.record("release", {"pod_key": "a/b"}, None)
    assert any("trace.py" in site for site in mon.report()["sites"])


def test_monitor_cross_thread_release_leaves_no_phantom_edges():
    """Plain Locks may be released by a thread other than the acquirer
    (handoff): the proxy must leave the acquiring thread's stack either
    way, or every later acquisition there records phantom edges."""
    with lockgraph.monitor(scope=None) as mon:
        a = threading.Lock()
        b = threading.Lock()
        a.acquire()
        t = threading.Thread(target=a.release)
        t.start()
        t.join()
        with b:   # a's stale entry would fabricate an a->b edge here
            pass
    # scope=None also sees stdlib Thread/Event internals (an edge from
    # a's site to threading.py is legitimately recorded at t.start()
    # while a is still held); the phantom this guards against is
    # specifically a->b — both sites in THIS file — after the handoff
    assert not any("test_lint" in frm and "test_lint" in to
                   for frm, to in mon.edges())


def test_monitor_reentrant_rlock_is_not_an_edge():
    with lockgraph.monitor(scope=None) as mon:
        r = threading.RLock()
        with r:
            with r:
                pass
    assert mon.edges() == {}
    assert mon.cycles() == []


def test_monitor_unwinds_when_cluster_constructor_fails():
    """A SimCluster that installs the monitor and then fails to build
    must not leak the process-wide threading patch. The seeded failure
    fires INSIDE _init_cluster (a slices value that is not a MeshSpec),
    i.e. after install() ran — the unwind path, not the pre-install
    validation."""
    import pytest

    from tpukube.core.config import load_config
    from tpukube.sim import SimCluster

    cfg = load_config(env={"TPUKUBE_LOCK_MONITOR": "1"})
    with pytest.raises(AttributeError):
        SimCluster(cfg, slices={"bad": None})
    assert threading.Lock is lockgraph._REAL_LOCK
    assert threading.RLock is lockgraph._REAL_RLOCK


def test_dynamic_detector_clean_across_sim_scenarios():
    """ISSUE 3 acceptance: the dynamic lock-order detector runs under
    sim scenarios 1-7 and reports ZERO inversion cycles — the declared
    partial order (decision -> pending -> gang -> ledger) is what the
    live daemons actually do under gangs, preemption, churn, and
    fault-telemetry load."""
    from tpukube.sim import scenarios

    with lockgraph.monitor() as mon:
        for i in range(1, 8):
            scenarios.run(i, None)
    rep = mon.report()
    assert rep["cycles"] == [], rep["cycles"]
    # substantive: it really observed the scheduling locks nesting
    assert rep["acquisitions"] > 1000
    edges = {(e["from"].rsplit(":", 1)[0], e["to"].rsplit(":", 1)[0])
             for e in rep["edges"]}
    assert ("tpukube/sched/extender.py", "tpukube/sched/gang.py") in edges
    assert ("tpukube/sched/gang.py", "tpukube/sched/state.py") in edges


def test_dynamic_detector_concurrent_stress_via_config_flag():
    """The lock_monitor config flag drives SimCluster instrumentation;
    a multi-threaded schedule/delete stress (webhook loop + lifecycle
    from many threads at once) must stay cycle-free."""
    from tpukube.core.config import load_config
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_LOCK_MONITOR": "1",
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        assert c.lock_monitor is not None

        def worker(k: int) -> None:
            for j in range(3):
                name = f"s{k}-{j}"
                c.schedule(c.make_pod(name, tpu=1))
                c.delete_pod(name)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report = c.lock_monitor.report()
    assert report["cycles"] == [], report["cycles"]
    assert report["acquisitions"] > 0
    # uninstalled with the cluster
    assert threading.Lock is lockgraph._REAL_LOCK


# -- federated lockgraph (ISSUE 18) ------------------------------------------

def test_federated_lockgraph_merges_fleet_and_is_clean():
    """The sharded plane under the monitor: the router's
    ``lockgraph_report()`` merges its own edges with every replica's
    (inproc replicas share the process-wide monitor and are listed
    without double-merging) and the fleet-wide cycle check is clean
    across the extended partial order (router/journal edges included)."""
    from tpukube.core.config import load_config
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_LOCK_MONITOR": "1",
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_SHARD_SLICES": "2",
        "TPUKUBE_SIM_MESH_DIMS": "2,2,4",
    })
    with SimCluster(cfg, in_process=True) as c:
        for i in range(8):
            c.schedule(c.make_pod(f"p{i}", tpu=1))
        rep = c.extender.lockgraph_report()
        assert rep is not None
        assert rep["cycles"] == [], rep["cycles"]
        assert rep["acquisitions"] > 0
        assert rep["replicas_reporting"] == ["r0", "r1"]
        # every replica_summary row carries its own report too — the
        # worker status surface the subprocess merge rides
        doc = c.extender.statusz()
        for row in doc["replicas"]:
            assert row["lock_graph"]["cycles"] == []
    assert threading.Lock is lockgraph._REAL_LOCK


def test_federated_lockgraph_off_is_off():
    """Monitor off: ``lockgraph_report()`` is None and replica
    summaries carry NO lock_graph key — the status wire shape is
    byte-identical to the pre-monitor plane."""
    from tpukube.core.config import load_config
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_PLANNER_REPLICAS": "2",
        "TPUKUBE_SHARD_SLICES": "2",
        "TPUKUBE_SIM_MESH_DIMS": "2,2,4",
    })
    with SimCluster(cfg, in_process=True) as c:
        c.schedule(c.make_pod("p0", tpu=1))
        assert c.extender.lockgraph_report() is None
        for row in c.extender.statusz()["replicas"]:
            assert "lock_graph" not in row
